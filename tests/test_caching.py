"""Compile/trace caching — the TPU analog of the reference's pervasive
``@inferred`` type-stability assertions (``test/pencils.jl:544-567``):
there, type instability silently costs every call; here, a cache-key
defect silently re-traces and re-compiles every call.  These tests pin
that repeated use hits the caches.
"""

import jax
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    PencilFFTPlan,
    Permutation,
    Ring,
    Topology,
    reshard,
    transpose,
)
from pencilarrays_tpu.parallel.routing import _compiled_route
from pencilarrays_tpu.parallel.transpositions import (_compiled_reshard,
                                                      _compiled_transpose)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def test_eager_transpose_reuses_executable(topo):
    shape = (12, 10, 8)
    pen = Pencil(topo, shape, (1, 2), permutation=Permutation(2, 0, 1))
    pen_y = pen.replace(decomp_dims=(0, 2))
    u = np.random.default_rng(0).standard_normal(shape)
    x = PencilArray.from_global(pen, u)

    transpose(x, pen_y)  # populate
    before = _compiled_transpose.cache_info()
    for _ in range(5):
        transpose(x, pen_y)
    after = _compiled_transpose.cache_info()
    assert after.misses == before.misses, "eager transpose re-traced"
    assert after.hits == before.hits + 5


def test_equal_pencils_share_cache_key(topo):
    """Pencils are value-hashable: an INDEPENDENTLY constructed equal
    pencil must hit the same compiled executable (no identity keying)."""
    shape = (12, 10, 8)
    pen_a = Pencil(topo, shape, (1, 2))
    pen_b = Pencil(topo, shape, (1, 2))  # distinct object, equal value
    assert pen_a == pen_b and hash(pen_a) == hash(pen_b)
    dst_a = pen_a.replace(decomp_dims=(0, 2))
    dst_b = pen_b.replace(decomp_dims=(0, 2))
    u = np.random.default_rng(1).standard_normal(shape)
    transpose(PencilArray.from_global(pen_a, u), dst_a)
    before = _compiled_transpose.cache_info()
    transpose(PencilArray.from_global(pen_b, u), dst_b)
    assert _compiled_transpose.cache_info().misses == before.misses


def test_methods_have_distinct_cache_keys(topo):
    """Frozen-dataclass methods key the cache by VALUE: AllToAll() !=
    Ring() but AllToAll() == AllToAll()."""
    shape = (12, 10, 8)
    pen = Pencil(topo, shape, (1, 2))
    dst = pen.replace(decomp_dims=(0, 2))
    u = np.random.default_rng(2).standard_normal(shape)
    x = PencilArray.from_global(pen, u)
    transpose(x, dst, method=AllToAll())
    before = _compiled_transpose.cache_info()
    transpose(x, dst, method=Ring())     # new key: must miss
    mid = _compiled_transpose.cache_info()
    assert mid.misses == before.misses + 1
    transpose(x, dst, method=Ring())     # same value: must hit
    assert _compiled_transpose.cache_info().misses == mid.misses


def test_reshard_compiles_exactly_once(topo):
    """ISSUE 4 satellite regression: repeated reshard() calls on the
    same configuration must trigger exactly ONE compile per path —
    counted as jit-executable cache misses on both the GSPMD
    (_compiled_reshard) and the routed (_compiled_route) caches."""
    shape = (12, 10, 14)
    pen_a = Pencil(topo, shape, (1, 2))
    pen_b = Pencil(topo, shape, (0, 1), permutation=Permutation(2, 0, 1))
    u = np.random.default_rng(4).standard_normal(shape)
    x = PencilArray.from_global(pen_a, u)

    reshard(x, pen_b, method=Gspmd())  # populate: exactly one miss
    g0 = _compiled_reshard.cache_info()
    for _ in range(5):
        reshard(x, pen_b, method=Gspmd())
    g1 = _compiled_reshard.cache_info()
    assert g1.misses == g0.misses, "GSPMD reshard re-jitted per call"
    assert g1.hits == g0.hits + 5

    reshard(x, pen_b)  # routed default: populate planner + executor
    r0 = _compiled_route.cache_info()
    g2 = _compiled_reshard.cache_info()
    for _ in range(5):
        reshard(x, pen_b)
    assert _compiled_route.cache_info().misses == r0.misses
    assert _compiled_reshard.cache_info().misses == g2.misses

    # donate=True is a DIFFERENT executable (one more miss), then cached
    # (fresh source per call: the donated buffer is dead afterwards on
    # backends that implement donation)
    reshard(PencilArray.from_global(pen_a, u), pen_b, method=Gspmd(),
            donate=True)
    d0 = _compiled_reshard.cache_info()
    reshard(PencilArray.from_global(pen_a, u), pen_b, method=Gspmd(),
            donate=True)
    assert _compiled_reshard.cache_info().misses == d0.misses


def test_jitted_plan_traces_once(topo):
    """A jitted closure over a plan is traced once across repeated calls
    (trace counter via a side-effect probe, the jax-recommended trick)."""
    shape = (12, 10, 8)
    plan = PencilFFTPlan(topo, shape, real=True, dtype=np.float64)
    traces = []

    @jax.jit
    def fwd(data):
        traces.append(1)
        return plan.forward(PencilArray(plan.input_pencil, data)).data

    u = np.random.default_rng(3).standard_normal(shape)
    x = PencilArray.from_global(plan.input_pencil, u)
    r1 = fwd(x.data)
    for _ in range(4):
        r2 = fwd(x.data)
    assert len(traces) == 1, f"jitted plan re-traced {len(traces)} times"
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
