"""Mesh coordination layer: consensus, leases, epochs, election.

The contracts under test (ISSUE 6 acceptance):

* **one agreed action** — at a step boundary every rank's status enters
  one deterministic merge; the mesh atomically picks ok / all-retry /
  all-restore / all-re-raise, with identical verdicts and epochs on
  every rank (two in-process ranks over a shared ``FileKV`` drill it
  without subprocesses);
* **agreed-checkpoint election** — ``common_latest_valid()`` returns
  the newest step valid on EVERY rank: the divergent-restore hazard
  (one rank's newest step torn → per-rank ``latest_valid()`` disagree)
  is regression-pinned;
* **peer health leases** — a peer that stops heartbeating (or never
  joins) surfaces as a typed ``PeerFailureError`` naming the rank, with
  a crash bundle — never an indefinite wait;
* **rank-addressed faults** — ``point:mode%rank<k>`` triggers only in
  the named rank's process;
* **degrade-to-local** — with the layer off (or ``world == 1``) the
  guarded_step path never builds a coordinator and single-process
  behavior is untouched.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import cluster, guard, obs
from pencilarrays_tpu.cluster import (ClusterAbortError,
                                      ConsensusTimeoutError,
                                      PeerFailureError, epoch)
from pencilarrays_tpu.cluster.consensus import Coordinator, merge_statuses
from pencilarrays_tpu.cluster.health import LeaseBoard
from pencilarrays_tpu.cluster.kv import FileKV
from pencilarrays_tpu.guard import IntegrityError
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.resilience import CheckpointManager, RetryPolicy, faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Every test starts with cluster/guard/obs disabled, faults
    cleared, epoch 0."""
    from pencilarrays_tpu.cluster import elastic as elastic_mod

    for var in (cluster.ENV_VAR, cluster.RANK_VAR, cluster.WORLD_VAR,
                cluster.LEASE_TTL_VAR, cluster.VERDICT_TIMEOUT_VAR,
                guard.ENV_VAR, obs.ENV_VAR, faults.ENV_VAR,
                elastic_mod.ENV_VAR, elastic_mod.TIMEOUT_VAR,
                elastic_mod.MIN_WORLD_VAR):
        monkeypatch.delenv(var, raising=False)
    cluster._reset_for_tests()
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    cluster._reset_for_tests()
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _pair(tmp_path, *, ttl=10.0, timeout=30.0, sub="kv"):
    kv = FileKV(os.path.join(str(tmp_path), sub))
    return (Coordinator(kv, 0, 2, lease_ttl=ttl, verdict_timeout=timeout),
            Coordinator(kv, 1, 2, lease_ttl=ttl, verdict_timeout=timeout))


def _run_ranks(*thunks):
    """Run one callable per rank on its own thread (the in-process
    two-rank mesh); re-raises the first failure, returns rank->result."""
    results, errors = {}, {}

    def wrap(r, fn):
        try:
            results[r] = fn()
        except BaseException as e:   # noqa: BLE001 - re-raised below
            errors[r] = e

    threads = [threading.Thread(target=wrap, args=(r, fn))
               for r, fn in enumerate(thunks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        raise errors[min(errors)]
    return results


# ---------------------------------------------------------------------------
# KV backend
# ---------------------------------------------------------------------------

def test_filekv_roundtrip(tmp_path):
    kv = FileKV(str(tmp_path))
    assert kv.try_get("a/b/r0") is None
    kv.set("a/b/r0", "hello")
    assert kv.try_get("a/b/r0") == "hello"
    assert kv.get("a/b/r0", 1.0) == "hello"
    kv.set("a/b/r0", "v2")          # overwrite is atomic publish
    assert kv.try_get("a/b/r0") == "v2"
    kv.delete("a/b/r0")
    assert kv.try_get("a/b/r0") is None
    kv.delete("a/b/r0")             # idempotent


def test_filekv_get_timeout_is_typed(tmp_path):
    kv = FileKV(str(tmp_path))
    t0 = time.monotonic()
    with pytest.raises(ConsensusTimeoutError) as ei:
        kv.get("never/r9", 0.3)
    assert time.monotonic() - t0 < 5.0
    assert ei.value.key == "never/r9"


def test_filekv_rejects_traversal_keys(tmp_path):
    kv = FileKV(str(tmp_path))
    with pytest.raises(ValueError):
        kv.set("../escape", "x")
    with pytest.raises(ValueError):
        kv.set("a/&bad", "x")


def test_filekv_get_on_wait_can_interrupt(tmp_path):
    kv = FileKV(str(tmp_path))

    def boom():
        raise PeerFailureError("peer gone", rank=1)

    with pytest.raises(PeerFailureError):
        kv.get("never/r1", 10.0, on_wait=boom)


# ---------------------------------------------------------------------------
# verdict merge (pure)
# ---------------------------------------------------------------------------

def test_merge_all_ok():
    v = merge_statuses([{"status": "ok"}, {"status": "ok"}])
    assert v["action"] == "ok" and v["ranks"] == []


def test_merge_retry_needs_everyones_budget():
    ok = {"status": "ok", "can_retry": True, "can_restore": True}
    bad = {"status": "integrity", "can_retry": True, "can_restore": True,
           "error": "sdc"}
    v = merge_statuses([ok, bad])
    assert v["action"] == "retry" and v["ranks"] == [1]
    # ONE exhausted rank forbids the all-retry (a half-mesh rerun would
    # deadlock): escalate to restore
    v = merge_statuses([dict(ok, can_retry=False), bad])
    assert v["action"] == "restore"


def test_merge_raise_when_nothing_left():
    v = merge_statuses([
        {"status": "hang", "can_retry": False, "can_restore": False,
         "error": "stuck"},
        {"status": "ok", "can_retry": False, "can_restore": True}])
    assert v["action"] == "raise"
    assert v["ranks"] == [0] and v["errors"][0] == "stuck"


# ---------------------------------------------------------------------------
# consensus rounds + epochs (two in-process ranks)
# ---------------------------------------------------------------------------

def test_two_rank_verdict_identical_and_epoch_advances(tmp_path):
    obs.enable(str(tmp_path / "obs"))
    c0, c1 = _pair(tmp_path)
    try:
        ok = {"status": "ok", "can_retry": True, "can_restore": True}
        bad = dict(ok, status="integrity", error="sdc")
        res = _run_ranks(lambda: c0.agree("step", ok),
                         lambda: c1.agree("step", bad))
        assert res[0] == res[1]
        assert res[0]["action"] == "retry" and res[0]["epoch"] == 1
        # a clean round does NOT advance the epoch
        res = _run_ranks(lambda: c0.agree("step", ok),
                         lambda: c1.agree("step", ok))
        assert res[0]["action"] == "ok" and res[0]["epoch"] == 1
        assert epoch.current() == 1
        events = obs.read_journal(str(tmp_path / "obs"))
        assert obs.lint_journal(events) == []
        advances = [e for e in events if e["ev"] == "guard.epoch"]
        assert [e["epoch"] for e in advances] == [1]
        verdicts = [e for e in events if e["ev"] == "cluster.verdict"]
        assert {e["action"] for e in verdicts} == {"retry", "ok"}
        snap = obs.snapshot()
        assert any(k.startswith("cluster.verdicts{")
                   for k in snap["counters"]), snap["counters"]
    finally:
        c0.shutdown()
        c1.shutdown()
        obs.disable()


def test_round_keys_garbage_collected(tmp_path):
    """The KV store must stay bounded on the armed path: round keys
    are GC'd with a one-round lag (≤ 2 live round keys per rank), not
    accumulated one per step boundary forever."""
    import glob

    c0, c1 = _pair(tmp_path)
    ok = {"status": "ok", "can_retry": True, "can_restore": False}
    try:
        for _ in range(5):
            _run_ranks(lambda: c0.agree("step", ok),
                       lambda: c1.agree("step", ok))
        round_files = glob.glob(
            os.path.join(str(tmp_path), "kv", "pa", "round", "**", "r*"),
            recursive=True)
        assert len(round_files) <= 4, sorted(round_files)
    finally:
        c0.shutdown()
        c1.shutdown()


def test_gate_tokens_case_insensitive(tmp_path, monkeypatch):
    """``OFF`` must be off and ``True`` must mean the jax KV backend —
    never a relative FileKV directory literally named ``True``."""
    from pencilarrays_tpu.cluster.kv import FileKV as _FileKV, resolve_kv

    for off in ("OFF", "Off", "FALSE", "0"):
        monkeypatch.setenv(cluster.ENV_VAR, off)
        assert not cluster.enabled(), off
        assert cluster.coordinator() is None
    for on in ("True", "ON", "1"):
        # the jax-KV backend (no client in this process -> a clear
        # RuntimeError, NOT a silent FileKV('True'))
        with pytest.raises(RuntimeError, match="no jax distributed KV"):
            resolve_kv(on)
    assert isinstance(resolve_kv(str(tmp_path / "kv")), _FileKV)


def test_agree_steps_intersection(tmp_path):
    c0, c1 = _pair(tmp_path)
    try:
        res = _run_ranks(lambda: c0.agree_steps("ck", [1, 2, 3]),
                         lambda: c1.agree_steps("ck", [1, 3, 4]))
        assert res[0] == res[1] == [1, 3]
        res = _run_ranks(lambda: c0.agree_steps("ck", [7]),
                         lambda: c1.agree_steps("ck", []))
        assert res[0] == res[1] == []
    finally:
        c0.shutdown()
        c1.shutdown()


# ---------------------------------------------------------------------------
# peer health leases
# ---------------------------------------------------------------------------

def test_lease_expiry_raises_typed_peer_failure(tmp_path):
    guard.enable(str(tmp_path / "bundles"))
    kv = FileKV(str(tmp_path / "kv"))
    a = LeaseBoard(kv, 0, 2, ttl=0.3)
    b = LeaseBoard(kv, 1, 2, ttl=0.3)
    a.start()
    b.start()
    a.check_peers()                  # both alive
    b.stop()                         # rank 1 "dies": renewals stop
    time.sleep(0.9)
    with pytest.raises(PeerFailureError) as ei:
        a.check_peers()
    e = ei.value
    assert e.rank == 1 and e.age_s > 0.3
    assert e.bundle and os.path.isdir(e.bundle)
    with open(os.path.join(e.bundle, "MANIFEST.json")) as f:
        man = json.load(f)
    assert man["reason"] == "peer-failure" and man["peer_rank"] == 1
    a.stop()


def test_never_joined_peer_fails_after_grace(tmp_path):
    guard.enable(str(tmp_path / "bundles"))
    kv = FileKV(str(tmp_path / "kv"))
    a = LeaseBoard(kv, 0, 2, ttl=0.2)
    a.join_grace = 0.4               # drills shrink the boot window
    a.start()
    a.check_peers()                  # inside the grace: no verdict yet
    time.sleep(0.5)
    with pytest.raises(PeerFailureError) as ei:
        a.check_peers()
    assert ei.value.rank == 1 and ei.value.age_s is None
    a.stop()


def test_transient_lease_read_failure_is_not_death(tmp_path):
    """A single unreadable lease read (KV weather, or an old-jaxlib
    delete+set renewal caught mid-flight) must NOT fabricate a peer
    death: staleness is judged against the last KNOWN renewal."""
    kv = FileKV(str(tmp_path / "kv"))
    a = LeaseBoard(kv, 0, 2, ttl=5.0)
    b = LeaseBoard(kv, 1, 2, ttl=5.0)
    a.join_grace = 0.0               # past the grace window
    a.start()
    b.start()
    a.check_peers()                  # b's lease read + remembered
    kv.delete("pa/lease/r1")         # one renewal caught mid-flight
    a.check_peers()                  # remembered timestamp: still alive
    b.renew()                        # the renewal lands
    a.check_peers()
    a.stop()
    b.stop()


def test_join_grace_knob(tmp_path, monkeypatch):
    monkeypatch.setenv(cluster.ENV_VAR, str(tmp_path / "kv"))
    monkeypatch.setenv(cluster.RANK_VAR, "0")
    monkeypatch.setenv(cluster.WORLD_VAR, "2")
    monkeypatch.setenv(cluster.JOIN_GRACE_VAR, "123.5")
    c = cluster.coordinator()
    assert c.leases.join_grace == 123.5
    cluster._reset_for_tests()


def test_lease_renewal_keeps_peer_alive(tmp_path):
    kv = FileKV(str(tmp_path / "kv"))
    a = LeaseBoard(kv, 0, 2, ttl=0.5, interval=0.1)
    b = LeaseBoard(kv, 1, 2, ttl=0.5, interval=0.1)
    a.start()
    b.start()
    for _ in range(4):               # > ttl of wall time, renewals riding
        time.sleep(0.2)
        a.check_peers()
        b.check_peers()
    a.stop()
    b.stop()


# ---------------------------------------------------------------------------
# agreed-checkpoint election: the divergent-restore regression
# ---------------------------------------------------------------------------

def _mk_state(truth):
    import jax

    topo = pa.Topology((1,), devices=jax.devices()[:1])
    pen = pa.Pencil(topo, truth.shape, (1,))
    return pen, pa.PencilArray.from_global(pen, truth)


def _tear(ckdir, step):
    """Flip one byte of a committed step's data file: the checkpoint
    still parses but its checksum verification must fail."""
    path = os.path.join(ckdir, f"step-{step:08d}", "data.bin")
    with open(path, "r+b") as f:
        f.seek(64)
        b = f.read(1)
        f.seek(64)
        f.write(bytes([b[0] ^ 0xFF]))


def test_common_latest_valid_agrees_on_oldest_common_step(tmp_path):
    """THE divergent-restore hazard, pinned: rank 0's newest step is
    torn, so its latest_valid() is 1 while rank 1's is 2 — a per-rank
    restore would load DIFFERENT steps.  common_latest_valid() must
    return 1 on BOTH ranks, and both restores must be bit-identical."""
    truth = np.random.default_rng(3).standard_normal((11, 9, 13))
    pen, u1 = _mk_state(truth)
    _, u2 = _mk_state(truth + 5.0)
    mgrs = {}
    for r in range(2):
        mgrs[r] = CheckpointManager(str(tmp_path / f"ck{r}"), keep=4)
        mgrs[r].save(1, {"u": u1})
        mgrs[r].save(2, {"u": u2})
    _tear(str(tmp_path / "ck0"), 2)
    # the hazard exists: local answers diverge
    assert mgrs[0].latest_valid() == 1
    assert mgrs[1].latest_valid() == 2
    c0, c1 = _pair(tmp_path)
    try:
        res = _run_ranks(
            lambda: mgrs[0].common_latest_valid(coordinator=c0),
            lambda: mgrs[1].common_latest_valid(coordinator=c1))
        assert res[0] == res[1] == 1
        backs = _run_ranks(
            lambda: pa.gather(mgrs[0].restore(1).read("u", pen)),
            lambda: pa.gather(mgrs[1].restore(1).read("u", pen)))
        assert np.array_equal(backs[0], truth)
        assert np.array_equal(backs[0], backs[1])
    finally:
        c0.shutdown()
        c1.shutdown()


def test_common_latest_valid_none_when_no_common_step(tmp_path):
    truth = np.random.default_rng(4).standard_normal((8, 6, 4))
    _, u = _mk_state(truth)
    m0 = CheckpointManager(str(tmp_path / "ck0"), keep=4)
    m1 = CheckpointManager(str(tmp_path / "ck1"), keep=4)
    m0.save(1, {"u": u})
    m1.save(2, {"u": u})
    c0, c1 = _pair(tmp_path)
    try:
        res = _run_ranks(lambda: m0.common_latest_valid(coordinator=c0),
                         lambda: m1.common_latest_valid(coordinator=c1))
        assert res[0] is None and res[1] is None
    finally:
        c0.shutdown()
        c1.shutdown()


def test_common_latest_valid_degrades_to_latest_valid(tmp_path):
    """No coordinator (layer off / world 1): exactly latest_valid()."""
    truth = np.random.default_rng(5).standard_normal((8, 6, 4))
    _, u = _mk_state(truth)
    m = CheckpointManager(str(tmp_path / "ck"), keep=4)
    m.save(3, {"u": u})
    assert m.common_latest_valid() == m.latest_valid() == 3
    assert m.valid_steps() == [3]


# ---------------------------------------------------------------------------
# distributed guarded_step (two in-process ranks)
# ---------------------------------------------------------------------------

def test_mesh_guarded_step_agreed_retry(tmp_path):
    """One rank's transient failure: the mesh agrees retry, EVERY rank
    reruns (the healthy one too — a half-mesh rerun would deadlock its
    collectives), both recover."""
    obs.enable(str(tmp_path / "obs"))
    c0, c1 = _pair(tmp_path)
    calls = {0: 0, 1: 0}

    def make(r, coord):
        def run():
            def step():
                calls[r] += 1
                if r == 1 and calls[r] == 1:
                    raise IntegrityError("sdc", hop="t", kind="sum")
                return r * 10 + calls[r]
            return guard.guarded_step(
                step, retry=RetryPolicy(max_attempts=3, base_delay=0.01),
                label="mesh-retry", coordinator=coord)
        return run

    try:
        res = _run_ranks(make(0, c0), make(1, c1))
        assert calls == {0: 2, 1: 2}       # BOTH ranks reran
        assert res == {0: 2, 1: 12}
        events = obs.read_journal(str(tmp_path / "obs"))
        assert obs.lint_journal(events) == []
        actions = [e["action"] for e in events
                   if e["ev"] == "cluster.verdict"]
        assert sorted(actions) == ["ok", "ok", "retry", "retry"]
    finally:
        c0.shutdown()
        c1.shutdown()
        obs.disable()


def test_mesh_guarded_step_agreed_raise_is_typed_everywhere(tmp_path):
    """Unrecoverable failure on one rank: the failing rank re-raises
    its own typed error, the HEALTHY rank raises ClusterAbortError
    naming it — nobody hangs, nobody acts alone."""
    c0, c1 = _pair(tmp_path)

    def rank0():
        with pytest.raises(ClusterAbortError) as ei:
            guard.guarded_step(lambda: 0,
                               retry=RetryPolicy(max_attempts=1),
                               label="mesh-raise", coordinator=c0)
        assert ei.value.ranks == (1,)
        assert "IntegrityError" in ei.value.errors[1]
        return True

    def rank1():
        def step():
            raise IntegrityError("sdc", hop="t", kind="sum")
        with pytest.raises(IntegrityError):
            guard.guarded_step(step, retry=RetryPolicy(max_attempts=1),
                               label="mesh-raise", coordinator=c1)
        return True

    try:
        res = _run_ranks(rank0, rank1)
        assert res == {0: True, 1: True}
    finally:
        c0.shutdown()
        c1.shutdown()


def test_mesh_guarded_step_restores_agreed_step(tmp_path):
    """Retry budget exhausted: the mesh restores the SAME elected step
    on both ranks and reruns bit-identically (rank 0's newest step is
    torn, so the agreed step is the older common one)."""
    truth = np.random.default_rng(7).standard_normal((11, 9, 13))
    pen, u1 = _mk_state(truth)
    pen2 = pa.Pencil(pen.topology, truth.shape, (0,))
    c0, c1 = _pair(tmp_path)
    mgrs, states = {}, {}
    for r in range(2):
        mgrs[r] = CheckpointManager(str(tmp_path / f"ck{r}"), keep=4)
        mgrs[r].save(1, {"u": u1})
        mgrs[r].save(2, {"u": _mk_state(truth + 5.0)[1]})
        states[r] = {"u": _mk_state(truth + 1000.0)[1]}   # diverged
    _tear(str(tmp_path / "ck0"), 2)
    calls = {0: 0, 1: 0}

    def make(r, coord):
        def run():
            def step():
                calls[r] += 1
                if r == 1 and calls[r] <= 2:
                    raise IntegrityError("sdc", hop="t", kind="sum")
                return pa.transpose(states[r]["u"], pen2)

            def restore_cb(ckpt):
                states[r]["u"] = ckpt.read("u", pen)

            return guard.guarded_step(
                step, ckpt_mgr=mgrs[r], restore=restore_cb,
                retry=RetryPolicy(max_attempts=2, base_delay=0.01),
                label="mesh-restore", coordinator=coord)
        return run

    try:
        res = _run_ranks(make(0, c0), make(1, c1))
        assert np.array_equal(pa.gather(res[0]), truth)
        assert np.array_equal(pa.gather(res[1]), truth)
    finally:
        c0.shutdown()
        c1.shutdown()


def test_mesh_guarded_step_non_ladder_error_unblocks_peers(tmp_path):
    """A non-ladder exception (app bug) still propagates untouched on
    the failing rank — but never as a SILENT one-sided exit: the rank
    posts a fatal status for the round, so the healthy peer gets a
    prompt typed ClusterAbortError (not a verdict-timeout burn), and
    the round counters stay aligned for the next step."""
    c0, c1 = _pair(tmp_path, timeout=60.0)

    def rank0():
        t0 = time.monotonic()
        with pytest.raises(ClusterAbortError) as ei:
            guard.guarded_step(lambda: 0, label="app-bug",
                               retry=RetryPolicy(max_attempts=1),
                               coordinator=c0)
        assert ei.value.ranks == (1,)
        assert "ValueError" in ei.value.errors[1]
        assert time.monotonic() - t0 < 30.0   # not a timeout burn
        # rounds still aligned: the NEXT step reaches consensus
        return guard.guarded_step(lambda: "next", label="app-bug",
                                  retry=RetryPolicy(max_attempts=1),
                                  coordinator=c0)

    def rank1():
        def step():
            raise ValueError("app bug, not SDC")
        with pytest.raises(ValueError):
            guard.guarded_step(step, label="app-bug",
                               retry=RetryPolicy(max_attempts=1),
                               coordinator=c1)
        return guard.guarded_step(lambda: "next", label="app-bug",
                                  retry=RetryPolicy(max_attempts=1),
                                  coordinator=c1)

    try:
        res = _run_ranks(rank0, rank1)
        assert res == {0: "next", 1: "next"}
    finally:
        c0.shutdown()
        c1.shutdown()


def test_mesh_guarded_step_peer_death_mid_step(tmp_path):
    """A rank that dies inside the step (its thread just stops
    heartbeating and never reaches the verdict exchange): the survivor
    gets a typed PeerFailureError from the lease check during its
    consensus wait — not a hang until the verdict timeout."""
    guard.enable(str(tmp_path / "bundles"))
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 2, lease_ttl=0.4, verdict_timeout=60.0)
    c1 = Coordinator(kv, 1, 2, lease_ttl=0.4, verdict_timeout=60.0)

    def rank0():
        t0 = time.monotonic()
        with pytest.raises(PeerFailureError) as ei:
            guard.guarded_step(lambda: 0, label="mesh-death",
                               retry=RetryPolicy(max_attempts=1),
                               coordinator=c0)
        assert ei.value.rank == 1
        assert time.monotonic() - t0 < 30.0   # lease-fast, not timeout
        return True

    def rank1():
        c1.shutdown()                 # "dies": lease renewals stop
        return True

    try:
        res = _run_ranks(rank0, rank1)
        assert res == {0: True, 1: True}
    finally:
        c0.shutdown()


# ---------------------------------------------------------------------------
# gate / identity / degrade-to-local
# ---------------------------------------------------------------------------

def test_gate_disabled_by_default_and_cheap():
    assert not cluster.enabled()
    assert cluster.coordinator() is None


def test_gate_world_one_degrades_to_local(tmp_path, monkeypatch):
    """Env armed but a single-process mesh: coordinator() is None (the
    local ladder runs untouched)."""
    monkeypatch.setenv(cluster.ENV_VAR, str(tmp_path / "kv"))
    assert cluster.enabled()
    assert cluster.world_size() == 1
    assert cluster.coordinator() is None


def test_gate_identity_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv(cluster.RANK_VAR, "3")
    monkeypatch.setenv(cluster.WORLD_VAR, "5")
    assert cluster.rank() == 3
    assert cluster.world_size() == 5


def test_guarded_step_local_path_never_builds_coordinator(monkeypatch):
    """Degrade contract (acceptance c): with the layer off, guarded_step
    must not even construct a Coordinator — the PR-5 local ladder runs
    as-is."""
    from pencilarrays_tpu.cluster import consensus as consensus_mod

    def boom(*a, **k):
        raise AssertionError("Coordinator built on the disabled path")

    monkeypatch.setattr(consensus_mod, "Coordinator", boom)
    assert guard.guarded_step(lambda: 42) == 42


def test_env_built_coordinator_and_reset(tmp_path, monkeypatch):
    monkeypatch.setenv(cluster.ENV_VAR, str(tmp_path / "kv"))
    monkeypatch.setenv(cluster.RANK_VAR, "0")
    monkeypatch.setenv(cluster.WORLD_VAR, "2")
    c = cluster.coordinator()
    assert c is not None and c.rank == 0 and c.world == 2
    assert cluster.coordinator() is c          # cached
    cluster.disable()
    assert cluster.coordinator() is None       # programmatic off wins
    cluster._reset_for_tests()
    assert cluster.coordinator() is not None   # env applies again


# ---------------------------------------------------------------------------
# rank-addressed fault injection (%rank<k>)
# ---------------------------------------------------------------------------

def test_faults_rank_selector_parse():
    (r,) = faults.parse("hop.exchange:corrupt%rank1@2")
    assert (r.point, r.mode, r.rank, r.first, r.times) == \
        ("hop.exchange", "corrupt", 1, 2, None)
    (r,) = faults.parse("hop.exchange:kill%rank2")
    assert (r.mode, r.rank, r.times) == ("kill", 2, 1)
    (r,) = faults.parse("io.write_block:torn%rank0*3@2")
    assert (r.mode, r.rank, r.times, r.first) == ("torn", 0, 3, 2)
    with pytest.raises(ValueError, match="rank<k>"):
        faults.parse("hop.exchange:corrupt%node1")
    with pytest.raises(ValueError):
        faults.parse("hop.exchange:corrupt%rank")


def test_faults_rank_selector_addresses_one_rank(monkeypatch):
    monkeypatch.setenv(cluster.RANK_VAR, "0")
    with faults.active("barrier:error%rank1"):
        assert faults.fire("barrier") is None      # not us: no trigger
        assert faults.hit_count("barrier") == 1    # counters still tick
    monkeypatch.setenv(cluster.RANK_VAR, "1")
    from pencilarrays_tpu.resilience.errors import InjectedFault

    with faults.active("barrier:error%rank1"):
        with pytest.raises(InjectedFault):
            faults.fire("barrier")


def test_faults_unselected_rules_unchanged():
    (r,) = faults.parse("io.open:error*2@3")
    assert r.rank is None


# ---------------------------------------------------------------------------
# recovery epochs: stamps in manifests, bundles, journal
# ---------------------------------------------------------------------------

def test_epoch_monotonic_and_journaled(tmp_path):
    obs.enable(str(tmp_path / "obs"))
    assert epoch.current() == 0
    assert epoch.advance("test") == 1
    assert epoch.set_current(5, "jump") == 5
    assert epoch.set_current(3, "rewind-ignored") == 5   # monotonic
    events = obs.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    assert [e["epoch"] for e in events if e["ev"] == "guard.epoch"] == [1, 5]
    obs.disable()


def test_epoch_stamped_into_checkpoint_manifest(tmp_path):
    truth = np.random.default_rng(8).standard_normal((8, 6, 4))
    _, u = _mk_state(truth)
    m = CheckpointManager(str(tmp_path / "ck"), keep=2)
    m.save(1, {"u": u})
    epoch.advance("test-advance")
    m.save(2, {"u": u})
    with open(str(tmp_path / "ck" / "step-00000001" / "MANIFEST.json")) as f:
        assert json.load(f)["epoch"] == 0
    with open(str(tmp_path / "ck" / "step-00000002" / "MANIFEST.json")) as f:
        assert json.load(f)["epoch"] == 1


def test_epoch_stamped_into_crash_bundle(tmp_path):
    guard.enable(str(tmp_path / "bundles"))
    epoch.set_current(7, "test")
    path = guard.write_crash_bundle("test", "epoch-stamp")
    with open(os.path.join(path, "MANIFEST.json")) as f:
        assert json.load(f)["epoch"] == 7


# ---------------------------------------------------------------------------
# elastic mesh reformation (ISSUE 8): leave, membership, reform, rejoin
# ---------------------------------------------------------------------------

from pencilarrays_tpu.cluster import (PeerLeftError, ReformError,  # noqa: E402
                                      elastic)


def test_merge_leave_action():
    """Every non-ok status being a clean 'leave' merges to action
    'leave' — planned scale-down, never a recovery verdict; a leave
    MIXED with a real failure still enters the recovery merge."""
    ok = {"status": "ok", "can_retry": True, "can_restore": True}
    bye = {"status": "leave", "can_retry": False, "can_restore": False}
    v = merge_statuses([ok, bye])
    assert v["action"] == "leave" and v["ranks"] == [1]
    bad = {"status": "integrity", "can_retry": True, "can_restore": True,
           "error": "sdc"}
    v = merge_statuses([bad, bye, ok])
    assert v["action"] in ("restore", "raise")   # leaver blocks all-retry


def test_graceful_leave_is_typed_not_a_failure(tmp_path):
    """Satellite: a rank that published ``cluster.leave`` before its
    lease lapsed surfaces as PeerLeftError — NOT PeerFailureError, no
    crash bundle, no ``cluster.peer_failures`` tick — and the journal
    carries the leave + observed-departure membership records."""
    obs.enable(str(tmp_path / "obs"))
    guard.enable(str(tmp_path / "bundles"))
    kv = FileKV(str(tmp_path / "kv"))
    a = LeaseBoard(kv, 0, 2, ttl=0.3)
    b = LeaseBoard(kv, 1, 2, ttl=0.3)
    a.start()
    b.start()
    a.check_peers()
    b.leave()
    time.sleep(0.9)
    with pytest.raises(PeerLeftError) as ei:
        a.check_peers()
    assert ei.value.rank == 1
    assert not isinstance(ei.value, PeerFailureError)
    assert not os.path.exists(str(tmp_path / "bundles"))   # no false alarm
    snap = obs.snapshot()
    assert not any(k.startswith("cluster.peer_failures")
                   for k in snap["counters"]), snap["counters"]
    events = obs.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    changes = [(e["rank"], e["change"]) for e in events
               if e["ev"] == "cluster.member"]
    assert (1, "leave") in changes      # announced by the leaver
    assert (1, "left") in changes       # observed by the survivor
    a.stop()
    obs.disable()


def test_live_ranks_excludes_dead_and_left(tmp_path):
    kv = FileKV(str(tmp_path / "kv"))
    boards = {r: LeaseBoard(kv, r, 3, ttl=0.4) for r in range(3)}
    for b in boards.values():
        b.start()
    time.sleep(0.1)
    assert boards[0].live_ranks() == [0, 1, 2]
    boards[1].leave()                    # clean departure
    boards[2].stop()                     # crash: renewals just stop
    time.sleep(0.9)
    assert boards[0].live_ranks() == [0]
    boards[0].stop()


def test_reform_shrinks_world_and_advances_epoch(tmp_path):
    """Two survivors of a 3-rank mesh reform together: same agreed
    membership/generation/epoch on both, dense reindex, and the
    reformed pair immediately reaches consensus in the new
    namespace."""
    obs.enable(str(tmp_path / "obs"))
    kv = FileKV(str(tmp_path / "kv"))
    coords = {r: Coordinator(kv, r, 3, lease_ttl=0.4, verdict_timeout=20)
              for r in range(3)}
    coords[2].shutdown()                 # rank 2 "dies"
    time.sleep(0.9)
    try:
        res = _run_ranks(
            lambda: elastic.reform(coords[0], reason="peer-failure",
                                   install=False),
            lambda: elastic.reform(coords[1], reason="peer-failure",
                                   install=False))
        m0, m1 = res[0].membership, res[1].membership
        assert m0.members == m1.members == [0, 1]
        assert m0.gen == m1.gen == 1
        assert m0.epoch == m1.epoch == 1
        assert (m0.new_rank, m1.new_rank) == (0, 1)
        assert m0.new_world == 2
        assert m0.namespace == m1.namespace
        assert epoch.current() == 1
        ok = {"status": "ok", "can_retry": True, "can_restore": False}
        post = _run_ranks(lambda: res[0].coordinator.agree("post", ok),
                          lambda: res[1].coordinator.agree("post", ok))
        assert post[0] == post[1] and post[0]["action"] == "ok"
        events = obs.read_journal(str(tmp_path / "obs"))
        assert obs.lint_journal(events) == []
        stages = [e["stage"] for e in events if e["ev"] == "cluster.reform"]
        assert stages.count("complete") == 2    # one per survivor
        drops = [(e["rank"], e["change"]) for e in events
                 if e["ev"] == "cluster.member"]
        assert (2, "drop") in drops
    finally:
        for r in res:
            res[r].coordinator.shutdown()
        obs.disable()


def test_reform_join_grows_world_back(tmp_path):
    """Rejoin: a replacement publishes a join request; the next
    reformation boundary admits it — the survivor and the joiner end
    with coordinators agreeing in the same reformed namespace."""
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 1, lease_ttl=5.0, verdict_timeout=20)
    out = {}

    def survivor():
        # wait until the join request is visible, then hit a
        # reformation boundary (operator-requested resize)
        kv.get("pa/join/sspare", 20.0)
        out["r"] = elastic.reform(c0, reason="resize", install=False)
        return True

    def joiner():
        out["j"] = elastic.request_join(kv, "spare", namespace="pa",
                                        timeout=30)
        return True

    try:
        _run_ranks(survivor, joiner)
        m = out["r"].membership
        assert m.members == [0] and m.joiners == ["spare"]
        assert m.new_world == 2
        jm = out["j"].membership
        assert jm.new_rank == 1 and jm.new_world == 2
        assert jm.namespace == m.namespace
        ok = {"status": "ok", "can_retry": True, "can_restore": False}
        post = _run_ranks(lambda: out["r"].coordinator.agree("post", ok),
                          lambda: out["j"].coordinator.agree("post", ok))
        assert post[0] == post[1] and post[0]["action"] == "ok"
        # the consumed request cannot re-admit a ghost at the next round
        assert kv.try_get("pa/join/sspare") is None
    finally:
        c0.shutdown()
        for k in out:
            out[k].coordinator.shutdown()


def test_elastic_gate_off_preserves_peer_failure(tmp_path, monkeypatch):
    """Acceptance: with the elastic gate off (the shipped default),
    elastic_step IS guarded_step — the PeerFailureError propagates
    untouched and reform() is never even called."""
    assert not elastic.enabled()
    monkeypatch.setattr(elastic, "reform",
                        lambda *a, **k: pytest.fail(
                            "reform() called on the disabled path"))
    guard.enable(str(tmp_path / "bundles"))
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 2, lease_ttl=0.3, verdict_timeout=20)
    time.sleep(0.8)    # rank 1 never joins; grace shrunk below
    c0.leases.join_grace = 0.5
    try:
        with pytest.raises(PeerFailureError):
            guard.elastic_step(lambda: 1, label="off",
                               retry=RetryPolicy(max_attempts=1),
                               coordinator=c0)
    finally:
        c0.shutdown()


def test_elastic_step_reforms_restores_and_reruns(tmp_path):
    """The new ladder rung end to end, in-process: rank 1 dies, rank 0
    reforms to world=1, restores the agreed step through the
    cross-decomposition read path, reruns, and returns a value
    bit-identical to ground truth."""
    truth = np.random.default_rng(9).standard_normal((11, 9, 13))
    pen, u1 = _mk_state(truth)
    pen2 = pa.Pencil(pen.topology, truth.shape, (0,))
    obs.enable(str(tmp_path / "obs"))
    guard.enable(str(tmp_path / "bundles"))
    elastic.enable()
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 2, lease_ttl=0.4, verdict_timeout=20)
    c1 = Coordinator(kv, 1, 2, lease_ttl=0.4, verdict_timeout=20)
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=4)
    mgr.save(1, {"u": u1})
    state = {"u": _mk_state(truth + 1000.0)[1]}   # diverged pre-restore

    def restore_cb(ckpt):
        state["u"] = ckpt.read("u", pen, verify="local")

    c1.shutdown()
    time.sleep(0.9)
    try:
        out = guard.elastic_step(
            lambda: pa.transpose(state["u"], pen2),
            ckpt_mgr=mgr, restore=restore_cb,
            retry=RetryPolicy(max_attempts=2, base_delay=0.01),
            label="elastic", coordinator=c0)
        assert np.array_equal(pa.gather(out), truth)
        events = obs.read_journal(str(tmp_path / "obs"))
        assert obs.lint_journal(events) == []
        stages = [e["stage"] for e in events if e["ev"] == "cluster.reform"]
        assert stages[0] == "begin" and stages[-1] == "complete"
        assert "restore" in stages
        rec = [(e["stage"], e.get("via")) for e in events
               if e["ev"] == "guard.recover"]
        assert ("reform", None) in rec
        assert rec[-1] == ("recovered", "reform")
        snap = obs.snapshot()
        assert snap["counters"].get("cluster.reforms{outcome=ok}") == 1.0
    finally:
        cluster._reset_for_tests()   # shuts down the installed coordinator
        obs.disable()


def test_plan_registry_rebuilt_on_reform(tmp_path):
    """Registered plan factories re-run at every reformation with the
    new topology context, and the compiled-executable caches are
    dropped (they are keyed by pencils of the dead mesh)."""
    from pencilarrays_tpu.parallel import transpositions as tr

    truth = np.random.default_rng(10).standard_normal((8, 6, 4))
    pen, u = _mk_state(truth)
    pen2 = pa.Pencil(pen.topology, truth.shape, (0,))
    pa.gather(pa.transpose(u, pen2))     # prime a compiled hop
    assert tr._compiled_transpose.cache_info().currsize > 0
    built = []

    def factory(ctx):
        built.append((ctx.membership.new_world, ctx.coordinator))
        return ("plan-for", ctx.membership.new_world)

    elastic.register_plan("fft-main", factory)
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 2, lease_ttl=0.4, verdict_timeout=20)
    c1 = Coordinator(kv, 1, 2, lease_ttl=0.4, verdict_timeout=20)
    c1.leave()
    time.sleep(0.9)
    try:
        r = elastic.reform(c0, reason="leave", install=False)
        assert built and built[0][0] == 1
        assert built[0][1] is r.coordinator
        assert elastic.plan("fft-main") == ("plan-for", 1)
        assert tr._compiled_transpose.cache_info().currsize == 0
    finally:
        c0.shutdown()
        r.coordinator.shutdown()


def test_reform_runs_under_hang_watchdog(tmp_path, monkeypatch):
    """Satellite bugfix: a survivor wedged during reformation (here: a
    rebuild callback that never returns) leaves a crash bundle and a
    typed HangTimeoutError — never a silent stall kept alive by its own
    fresh heartbeat."""
    from pencilarrays_tpu.guard import HangTimeoutError

    guard.enable(str(tmp_path / "bundles"))
    monkeypatch.setenv(guard.TIMEOUT_VAR, "1.0")
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 1, lease_ttl=5.0, verdict_timeout=20)
    try:
        with pytest.raises(HangTimeoutError) as ei:
            elastic.reform(c0, reason="wedged", install=False,
                           rebuild=lambda ctx: time.sleep(30))
        assert ei.value.bundle and os.path.isdir(ei.value.bundle)
        snap = obs.snapshot()
        assert snap["counters"].get(
            "cluster.reforms{outcome=failed}", 0) >= 0
    finally:
        c0.shutdown()


def test_min_world_floor_is_enforced(tmp_path, monkeypatch):
    monkeypatch.setenv(elastic.MIN_WORLD_VAR, "2")
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 2, lease_ttl=0.3, verdict_timeout=20)
    time.sleep(0.7)                      # rank 1 gone (never heartbeats)
    try:
        with pytest.raises(ReformError, match="MIN_WORLD"):
            elastic.reform(c0, reason="peer-failure", install=False)
    finally:
        c0.shutdown()


def test_reset_clears_elastic_state():
    """Satellite bugfix: drills must not leak elastic gate/generation/
    registry state into later tests."""
    elastic.enable()
    elastic.register_plan("x", lambda ctx: 1)
    elastic._note_gen(7)
    cluster._reset_for_tests()
    assert not elastic.enabled()
    assert elastic.plans() == {}
    assert elastic._gen == 0


def test_announce_leave_at_step_boundary(tmp_path):
    """The boundary-time departure path: a rank flagged via
    announce_leave() publishes status 'leave' at its next step
    boundary — the leaver exits the step cleanly WITH its result, the
    survivor gets an immediate typed PeerLeftError (no ttl wait), and
    nobody writes a crash bundle."""
    guard.enable(str(tmp_path / "bundles"))
    c0, c1 = _pair(tmp_path, ttl=30.0)   # huge ttl: leases CANNOT expire

    def survivor():
        t0 = time.monotonic()
        with pytest.raises(PeerLeftError) as ei:
            guard.guarded_step(lambda: "survivor",
                               retry=RetryPolicy(max_attempts=1),
                               label="drain", coordinator=c0)
        assert ei.value.rank == 1
        assert time.monotonic() - t0 < 20.0   # boundary, not ttl
        return True

    def leaver():
        c1.announce_leave()
        out = guard.guarded_step(lambda: "last-step",
                                 retry=RetryPolicy(max_attempts=1),
                                 label="drain", coordinator=c1)
        assert out == "last-step"             # exits WITH its result
        c1.leave()
        return True

    try:
        res = _run_ranks(survivor, leaver)
        assert res == {0: True, 1: True}
        assert not os.path.exists(str(tmp_path / "bundles"))
    finally:
        c0.shutdown()
        c1.shutdown()


def test_failed_reform_leaves_old_coordinator_alive(tmp_path, monkeypatch):
    """Review hardening: a FAILED reformation must not leave this rank
    with a heartbeat-dead coordinator (peers would declare it failed
    after one ttl) nor leak the half-built new world's heartbeat into
    the reformed namespace."""
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 1, lease_ttl=0.4, verdict_timeout=20)

    def boom(ctx):
        raise RuntimeError("replan exploded")

    try:
        with pytest.raises(RuntimeError, match="replan exploded"):
            elastic.reform(c0, reason="x", install=False, rebuild=boom)
        # the OLD lease is still being renewed (shutdown would stop it)
        time.sleep(0.9)
        assert c0.leases.peer_age(0) is not None
        assert c0.leases.peer_age(0) <= 0.4
        # the half-built generation's lease is NOT being renewed
        raw = kv.try_get("pa.g1/lease/r0")
        if raw is not None:
            time.sleep(0.9)
            assert float(json.loads(kv.try_get("pa.g1/lease/r0"))["t"]) \
                == float(json.loads(raw)["t"])
    finally:
        c0.shutdown()
