"""Validation of the analytic collective byte model (round-3 VERDICT
item 5): for every (device count) x (method) x (divisible/ragged shape)
configuration, the per-chip collective op counts AND byte volumes
measured from the compiled HLO must EQUAL ``transpose_cost``'s analytic
padded-tile prediction — so a packing regression that doubled wire
bytes fails loudly.  The TPU analog of the reference's per-peer
send-size accounting (``Transpositions.jl:383-389``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilFFTPlan,
    Ring,
    Topology,
    transpose_cost,
)
from pencilarrays_tpu.analysis import spmd


def _measured(pin, pout, extra_dims, dtype, method):
    # ONE shared extractor (analysis/spmd.py) — the former per-test
    # jit->lower->compile->collective_stats pipeline, typed
    return spmd.trace_transpose(pin, pout, extra_dims, dtype,
                                method).stats()


TOPOS = [(2,), (4,), (2, 2), (8,), (4, 2)]
METHODS = [AllToAll(), Ring()]


@pytest.mark.parametrize("dims", TOPOS)
@pytest.mark.parametrize("method", METHODS)
def test_hop_bytes_match_model(devices, dims, method):
    """Divisible AND ragged hops, across 1-D/2-D meshes of 2/4/8
    devices: measured == predicted, exactly."""
    n = int(np.prod(dims))
    topo = Topology(dims, devices=jax.devices()[:n])
    M = len(dims)
    for shape in [(16, 12, 20), (11, 9, 13)]:
        pin = Pencil(topo, shape, tuple(range(1, M + 1)))
        pout = Pencil(topo, shape, (0,) + tuple(range(2, M + 1)))
        for extra, dtype in [((), jnp.float32), ((3,), jnp.complex64)]:
            expect = transpose_cost(pin, pout, extra, dtype, method)
            got = _measured(pin, pout, extra, dtype, method)
            assert got == expect, (dims, shape, extra, method, got, expect)


def test_ragged_ring_fewer_rounds(devices):
    """The ragged-aware Ring's G-1 rounds (G nonempty participants) are
    what the model predicts: n=9 over P=8 runs 4 rounds, not 7."""
    topo = Topology((8,))
    pin = Pencil(topo, (9, 9, 4), (0,))
    pout = Pencil(topo, (9, 9, 4), (1,))
    cost = transpose_cost(pin, pout, (), jnp.float32, Ring())
    assert cost["collective-permute"]["count"] == 4  # G = ceil(9/2) = 5
    got = _measured(pin, pout, (), jnp.float32, Ring())
    assert got == cost


def test_gspmd_priced_from_hlo(devices):
    """Gspmd hops have no ANALYTIC model, but since ISSUE 4 they are
    priced from their own partitioned HLO (``gspmd_reshard_cost``) —
    the price must equal what the executed transpose actually compiles
    to, so Auto/route comparisons against Gspmd are real."""
    topo = Topology((4,), devices=jax.devices()[:4])
    pin = Pencil(topo, (8, 8), (0,))
    pout = Pencil(topo, (8, 8), (1,))
    cost = transpose_cost(pin, pout, method=Gspmd())
    assert cost and sum(v["bytes"] for v in cost.values()) > 0
    assert cost == _measured(pin, pout, (), jnp.float32, Gspmd())


def test_fft_plan_costs_match_compiled(devices):
    """The whole-plan predicted cost (per-hop dtypes included: the first
    hop of an r2c plan is already complex) equals the compiled forward
    program's measured collectives — for both methods, with extra dims,
    on the asymmetric flagship shape."""
    topo = Topology((4, 2))
    for method in METHODS:
        plan = PencilFFTPlan(topo, (16, 12, 20), real=True, method=method)
        for extra in [(), (3,)]:
            measured = spmd.trace_plan(plan, extra).stats()
            assert measured == plan.collective_costs(extra), (
                method, extra, measured, plan.collective_costs(extra))


def test_extra_dims_scale_bytes_linearly_count_fixed(devices):
    """ISSUE 9 regression pin: batched hops fold the batch into each
    hop's SINGLE collective — ``transpose_cost`` must scale bytes
    linearly in ``extra_dims`` while the collective count stays fixed,
    for every explicit method (the extra_dims path was previously only
    exercised as a carrier, never cost-asserted)."""
    from pencilarrays_tpu.parallel.transpositions import Pipelined

    for dims in [(4,), (2, 2), (8,)]:
        n = int(np.prod(dims))
        topo = Topology(dims, devices=jax.devices()[:n])
        M = len(dims)
        for shape in [(16, 12, 20), (11, 9, 13)]:
            pin = Pencil(topo, shape, tuple(range(1, M + 1)))
            pout = Pencil(topo, shape, (0,) + tuple(range(2, M + 1)))
            for method in [AllToAll(), Ring(), Pipelined(chunks=2)]:
                base = transpose_cost(pin, pout, (), jnp.complex64,
                                      method)
                for B in (2, 3, 8):
                    got = transpose_cost(pin, pout, (B,), jnp.complex64,
                                         method)
                    assert set(got) == set(base)
                    for op in base:
                        assert got[op]["count"] == base[op]["count"], (
                            dims, shape, method, B, got, base)
                        assert got[op]["bytes"] == B * base[op]["bytes"], (
                            dims, shape, method, B, got, base)


def test_batched_hop_cost_matches_compiled_hlo(devices):
    """The batched prediction is HLO-true, not just self-consistent:
    a ragged batched Pipelined hop (chunk axis chosen over the shape
    INCLUDING the batch dims) compiles to exactly the predicted
    collectives."""
    from pencilarrays_tpu.parallel.transpositions import Pipelined

    topo = Topology((4,), devices=jax.devices()[:4])
    pin = Pencil(topo, (11, 9, 13), (1,))
    pout = Pencil(topo, (11, 9, 13), (0,))
    for method in [AllToAll(), Ring(), Pipelined(chunks=2)]:
        expect = transpose_cost(pin, pout, (5,), jnp.complex64, method)
        got = _measured(pin, pout, (5,), jnp.complex64, method)
        assert got == expect, (method, got, expect)


def test_batched_plan_costs_match_compiled(devices):
    """``PencilFFTPlan(batch=B)``: the default-priced collective_costs
    (extra_dims = batch_dims) equal the compiled batched program's
    measured stats, and the per-op counts equal the UNBATCHED program's
    — the amortization claim, end to end on the whole plan."""
    topo = Topology((4, 2))
    plan = PencilFFTPlan(topo, (16, 12, 20), real=True, batch=3)
    measured = spmd.trace_plan(plan, (3,)).stats()
    assert measured == plan.collective_costs()
    per_sample = plan.collective_costs(())
    for op, c in measured.items():
        assert c["count"] == per_sample[op]["count"]
        assert c["bytes"] == 3 * per_sample[op]["bytes"]


def test_backward_costs_equal_forward(devices):
    """Hop shapes are symmetric: the backward program's collectives
    match the same model."""
    topo = Topology((4, 2))
    plan = PencilFFTPlan(topo, (16, 12, 20), real=True)
    assert (spmd.trace_plan(plan, (3,), "backward").stats()
            == plan.collective_costs((3,)))


def test_r2c_wire_bytes_pinned_no_double_count(devices):
    """ISSUE 13 satellite: the PR-9 Hermitian-half byte accounting
    combines with the wire's ÷2 precision factor WITHOUT
    double-counting — exact figures pinned.

    shape (16, 12, 10) r2c over topo (2, 4): stage 0's rfft shrinks
    dim 0 to 16//2+1 = 9 (ceil-padded to 10 over P=2), so both
    exchange hops move 180 c64 elements per chip (hop 1 operand
    extents (10, 6, 3); hop 2 (5, 12, 3)) — 1440 B each at full
    precision, 2880 total.  At wire_dtype="bf16" each element ships
    split-complex as 2 x 2 bytes = 720 B per hop, 1440 total: exactly
    half, collective counts unchanged, and the compiled HLO (forward
    AND backward) agrees byte-for-byte."""
    topo = Topology((2, 4))
    full = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32)
    wired = PencilFFTPlan(topo, (16, 12, 10), real=True,
                          dtype=jnp.float32, wire_dtype="bf16")
    assert full.collective_costs() == {
        "all-to-all": {"count": 2, "bytes": 2880}}
    assert wired.collective_costs() == {
        "all-to-all": {"count": 2, "bytes": 1440}}
    assert spmd.trace_plan(wired, ()).stats() == wired.collective_costs()
    assert (spmd.trace_plan(wired, (), "backward").stats()
            == wired.collective_costs())
    # batched: bytes scale xB on the wire figure, count fixed
    batched = PencilFFTPlan(topo, (16, 12, 10), real=True,
                            dtype=jnp.float32, wire_dtype="bf16",
                            batch=3)
    assert batched.collective_costs() == {
        "all-to-all": {"count": 2, "bytes": 4320}}
    assert spmd.trace_plan(batched, (3,)).stats() == \
        batched.collective_costs()
