"""Free-function API parity with the reference export list
(``src/PencilArrays.jl:35-39``, ``src/Pencils/Pencils.jl:13-20``)."""

import numpy as np
import pytest

import pencilarrays_tpu as pa


@pytest.fixture
def setup(devices):
    topo = pa.Topology((2, 4))
    pen = pa.Pencil(topo, (12, 10, 8), (1, 2),
                    permutation=pa.Permutation(2, 0, 1),
                    timer=pa.TimerOutput("t"))
    u = np.random.default_rng(0).standard_normal((12, 10, 8, 3))
    x = pa.PencilArray.from_global(pen, u)
    return topo, pen, x, u


def test_every_reference_export_exists():
    # src/PencilArrays.jl:35-39 + src/Pencils/Pencils.jl:13-20
    for name in [
        "PencilArray", "GlobalPencilArray", "PencilArrayCollection",
        "ManyPencilArray",
        "pencil", "permutation", "gather", "global_view",
        "ndims_extra", "ndims_space", "extra_dims", "sizeof_global",
        "Pencil", "MPITopology", "Permutation", "NoPermutation",
        "MemoryOrder", "LogicalOrder", "decomposition",
        "get_comm", "timer", "topology",
        "range_local", "range_remote", "size_local", "size_global",
        "to_local", "length_local", "length_global",
    ]:
        assert hasattr(pa, name), f"missing export: {name}"


def test_free_functions_dispatch(setup):
    topo, pen, x, u = setup
    assert pa.pencil(x) is pen
    assert pa.permutation(x) == pa.Permutation(2, 0, 1)
    assert pa.permutation(pen) == pa.Permutation(2, 0, 1)
    assert pa.decomposition(x) == (1, 2)
    assert pa.topology(pen) is topo
    assert pa.get_comm(topo) is topo.mesh
    assert pa.get_comm(x) is topo.mesh
    assert pa.timer(x) is pen.timer
    assert pa.extra_dims(x) == (3,)
    assert pa.ndims_extra(x) == 1
    assert pa.ndims_space(x) == 3
    assert pa.sizeof_global(x) == 12 * 10 * 8 * 3 * 8
    assert pa.range_local(x)[0] == range(0, 12)
    assert pa.range_remote(pen, 7)[2] == range(6, 8)
    assert pa.size_local(pen, (1, 3)) == (12, 5, 2)
    assert pa.size_global(x) == (12, 10, 8, 3)
    assert pa.size_global(pen, pa.MemoryOrder) == (8, 12, 10)
    assert pa.length_local(pen) == 12 * 5 * 2
    assert pa.length_global(pen) == 960
    assert pa.to_local(pen, (5, 6, 7), (1, 3)) == (5, 1, 1)
    assert pa.MPITopology is pa.Topology
    assert pa.GlobalPencilArray is pa.PencilArray
