"""DiffEq-ecosystem interop — parity with the reference extension
(``ext/PencilArraysDiffEqExt.jl:5-9``) and its property test
(``test/ode.jl:59-74``): a third-party adaptive integrator driven through
the global WRMS norm hook chooses the SAME dt under every decomposition.

When diffrax is installed the real ``diffeqsolve`` path runs; the
calling-convention tests (pytree state through jax control flow +
``norm=`` hook) always run, so the hook cannot rot in images without
diffrax.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Topology, gather
from pencilarrays_tpu.interop import (
    diffeqsolve, diffrax_available, global_wrms_norm,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


SHAPE = (11, 9, 6)  # ragged: padding exists on the 8-device mesh


def make_state(pen, seed=0):
    u = np.random.default_rng(seed).standard_normal(SHAPE)
    return u, PencilArray.from_global(pen, u)


def test_norm_matches_ground_truth_and_masks_padding(topo):
    pen = Pencil(topo, SHAPE, (1, 2))
    u, x = make_state(pen)
    # poison padding via scalar arithmetic (touches padded entries too)
    x = (x + 7.0) - 7.0
    expect = np.sqrt(np.mean(u ** 2))
    assert np.isclose(float(global_wrms_norm(x)), expect, rtol=1e-10)
    # mixed pytree: PencilArray + plain auxiliaries
    state = {"field": x, "aux": jnp.asarray([3.0, 4.0])}
    expect_mixed = np.sqrt((np.sum(u ** 2) + 25.0) / (u.size + 2))
    assert np.isclose(float(global_wrms_norm(state)), expect_mixed,
                      rtol=1e-10)


def _adaptive_solve(pen, n_steps=25, rtol=1e-5, atol=1e-8):
    """Stand-in adaptive controller speaking the diffrax convention:
    pytree state, scaled-error ``norm=`` hook, PI-less dt control.
    Returns the dt sequence and final state — the observable the
    reference's ode.jl property test compares across decompositions."""
    _, y = make_state(pen, seed=3)

    def f(t, y):  # du/dt = -u * (1 + 0.5 sin t): smooth decay
        return y * (-(1.0 + 0.5 * jnp.sin(t)))

    t, dt = jnp.zeros(()), jnp.asarray(0.05)
    dts = []
    for _ in range(n_steps):
        k1 = f(t, y)
        k2 = f(t + dt, y + k1 * dt)
        y_new = y + (k1 + k2) * (0.5 * dt)
        err = (k2 - k1) * (0.5 * dt)
        scaled = err.map(
            lambda e, a, b: e / (atol + rtol * jnp.maximum(jnp.abs(a),
                                                           jnp.abs(b))),
            y, y_new)
        enorm = global_wrms_norm(scaled)
        accept = enorm <= 1.0
        y = jax.tree_util.tree_map(
            lambda a, b: jnp.where(accept, a, b), y_new, y)
        t = t + jnp.where(accept, dt, 0.0)
        dt = dt * jnp.clip(0.9 * jnp.maximum(enorm, 1e-10) ** (-1 / 2),
                           0.2, 5.0)
        dts.append(float(dt))
    return np.asarray(dts), y


def test_decomposition_independent_dt(topo, devices):
    """test/ode.jl:59-74 parity: the dt trajectory chosen by the
    adaptive controller is identical on a 1-device and an 8-device
    mesh."""
    pen8 = Pencil(topo, SHAPE, (1, 2))
    topo1 = Topology((1,), devices=jax.devices()[:1])
    pen1 = Pencil(topo1, SHAPE, (2,))  # decomposed over the size-1 axis
    dts8, y8 = _adaptive_solve(pen8)
    dts1, y1 = _adaptive_solve(pen1)
    np.testing.assert_allclose(dts8, dts1, rtol=1e-12)
    np.testing.assert_allclose(gather(y8), gather(y1), rtol=1e-12)


def test_pencilarray_state_through_jax_control_flow(topo):
    """diffrax's core requirement: the state flows through scan/while as
    a pytree (flatten -> sharded leaf -> unflatten), with the norm hook
    traced inside."""
    pen = Pencil(topo, SHAPE, (1, 2))
    u, y0 = make_state(pen, seed=4)

    @jax.jit
    def rollout(y):
        def body(carry, _):
            y = carry
            y = y * 0.5
            return y, global_wrms_norm(y)

        return jax.lax.scan(body, y, None, length=4)

    y_final, norms = rollout(y0)
    assert isinstance(y_final, PencilArray)
    expect = np.sqrt(np.mean(u ** 2)) * np.array([0.5, 0.25, 0.125, 0.0625])
    np.testing.assert_allclose(np.asarray(norms), expect, rtol=1e-6)


def test_diffeqsolve_gating():
    if diffrax_available():
        pytest.skip("covered by test_diffeqsolve_real")
    with pytest.raises(ImportError, match="diffrax"):
        diffeqsolve(None, None, 0.0, 1.0, 0.1, None)


@pytest.fixture
def stub_diffrax(monkeypatch):
    """Install tests/diffrax_stub.py as ``diffrax`` so the REAL wrapper
    (interop.diffeqsolve) executes end-to-end.  Real-package parity
    still awaits the dependency (not installed in this image); the stub
    pins the wiring — controller construction, norm-hook plumbing,
    kwarg passthrough — against rot."""
    if diffrax_available():
        pytest.skip("real diffrax present; stub unnecessary")
    import diffrax_stub
    monkeypatch.setitem(__import__("sys").modules, "diffrax", diffrax_stub)
    return diffrax_stub


def test_diffeqsolve_stub_executes_wrapper(topo, stub_diffrax):
    """interop.diffeqsolve end-to-end through the stub: the default
    PIDController it builds must carry global_wrms_norm (observed via a
    counting wrapper at the norm seam), drive accept/reject, and solve
    the decay ODE on a PencilArray state."""
    diffrax = stub_diffrax
    pen = Pencil(topo, SHAPE, (1, 2))
    u, y0 = make_state(pen, seed=5)

    calls = {"n": 0}
    orig_ctor = diffrax.PIDController

    def counting_ctor(*, rtol, atol, norm):
        assert norm is global_wrms_norm  # the wrapper's default hook
        def counted(y):
            calls["n"] += 1
            return norm(y)
        return orig_ctor(rtol=rtol, atol=atol, norm=counted)

    diffrax.PIDController = counting_ctor
    try:
        term = diffrax.ODETerm(lambda t, y, args: y * (-1.0))
        # dt0 deliberately too coarse: forces at least one rejection, so
        # the controller's accept/reject seam demonstrably executes
        sol = diffeqsolve(term, diffrax.Heun(), 0.0, 1.0, 0.9, y0,
                          rtol=1e-5, atol=1e-8,
                          saveat=diffrax.SaveAt(t1=True))
    finally:
        diffrax.PIDController = orig_ctor
    (y1,) = jax.tree_util.tree_leaves(
        sol.ys, is_leaf=lambda x: isinstance(x, PencilArray))
    np.testing.assert_allclose(gather(y1), u * np.exp(-1.0), rtol=1e-4)
    assert sol.stats["num_rejected_steps"] >= 1
    assert calls["n"] >= sol.stats["num_accepted_steps"]


def test_diffeqsolve_stub_controller_override(topo, stub_diffrax):
    """A caller-supplied stepsize_controller kwarg must override the
    default global-norm controller (the wrapper's documented escape
    hatch)."""
    diffrax = stub_diffrax
    pen = Pencil(topo, SHAPE, (1, 2))
    _, y0 = make_state(pen, seed=6)
    mine = diffrax.PIDController(rtol=1e-3, atol=1e-6,
                                 norm=global_wrms_norm)
    term = diffrax.ODETerm(lambda t, y, args: y * (-1.0))
    sol = diffeqsolve(term, diffrax.Heun(), 0.0, 0.5, 0.1, y0,
                      stepsize_controller=mine)
    assert sol.stats["num_accepted_steps"] >= 1


@pytest.mark.skipif(not diffrax_available(), reason="diffrax not installed")
def test_diffeqsolve_real(topo):
    """The real ecosystem path, when the package is present: decay ODE on
    a PencilArray state with the global-norm controller."""
    import diffrax

    pen = Pencil(topo, SHAPE, (1, 2))
    u, y0 = make_state(pen, seed=5)
    term = diffrax.ODETerm(lambda t, y, args: y * (-1.0))
    sol = diffeqsolve(term, diffrax.Heun(), 0.0, 1.0, 0.05, y0,
                      rtol=1e-6, atol=1e-9,
                      saveat=diffrax.SaveAt(t1=True))
    (y1,) = jax.tree_util.tree_leaves(
        sol.ys, is_leaf=lambda x: isinstance(x, PencilArray))
    np.testing.assert_allclose(gather(y1), u * np.exp(-1.0), rtol=1e-4)
