"""Dtype genericity sweep — the analog of the reference's backend-
genericity tests (``test/array_types.jl``): the whole pipeline (construct,
transpose both methods, reduce, gather) must work for every element type
the hardware path supports, with bit-exact data movement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    gather,
    transpose,
)
from pencilarrays_tpu import ops

DTYPES = [
    jnp.float32,
    jnp.float64,
    jnp.float16,
    jnp.bfloat16,
    jnp.complex64,
    jnp.complex128,
    jnp.int32,
    jnp.int64,
    jnp.int16,
    jnp.uint8,
    jnp.bool_,
]


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def sample(shape, dtype):
    rng = np.random.default_rng(0)
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return rng.integers(0, 2, shape).astype(bool)
    if np.issubdtype(dt, np.complexfloating):
        return (rng.standard_normal(shape)
                + 1j * rng.standard_normal(shape)).astype(dt)
    if np.issubdtype(dt, np.integer):
        info = np.iinfo(dt)
        return rng.integers(max(info.min, -100), min(info.max, 100),
                            shape).astype(dt)
    return rng.standard_normal(shape).astype(dt)


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: np.dtype(d).name)
def test_transpose_roundtrip_every_dtype(topo, dtype):
    shape = (10, 11, 12)
    u = sample(shape, dtype)
    pen_a = Pencil(topo, shape, (1, 2), permutation=Permutation(2, 0, 1))
    pen_b = Pencil(topo, shape, (0, 2))
    x = PencilArray.from_global(pen_a, u)
    assert x.dtype == np.dtype(dtype)
    for method in (AllToAll(), Gspmd()):
        y = transpose(x, pen_b, method=method)
        back = transpose(y, pen_a, method=method)
        got = gather(back)
        if np.dtype(dtype).name == "bfloat16":
            np.testing.assert_array_equal(got.view(np.uint16),
                                          u.view(np.uint16))
        else:
            np.testing.assert_array_equal(got, u)


@pytest.mark.parametrize("dtype", [jnp.float16, jnp.bfloat16, jnp.int16,
                                   jnp.uint8],
                         ids=lambda d: np.dtype(d).name)
def test_reductions_narrow_dtypes(topo, dtype):
    shape = (9, 11, 13)  # ragged: masking must hold for narrow types too
    u = sample(shape, dtype)
    pen = Pencil(topo, shape, (1, 2))
    x = PencilArray.from_global(pen, u)
    assert float(ops.maximum(x)) == pytest.approx(float(u.max()))
    assert float(ops.minimum(x)) == pytest.approx(float(u.min()))


def test_bool_any_all_ragged(topo):
    shape = (9, 11, 13)
    pen = Pencil(topo, shape, (1, 2))
    u = np.ones(shape, dtype=bool)
    x = PencilArray.from_global(pen, u)
    assert bool(ops.all(x))  # padding False must be masked
    u2 = np.zeros(shape, dtype=bool)
    u2[8, 10, 12] = True
    assert bool(ops.any(PencilArray.from_global(pen, u2)))


def _jaxpr_dtypes(fn, *args):
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    seen = set()

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            # outvars only: weak-typed python-scalar INPUTS (e.g. dt)
            # appear as f64 consts under x64 but never promote results
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is not None and hasattr(aval, "dtype"):
                    seen.add(str(aval.dtype))
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)

    walk(closed.jaxpr)
    return seen


def test_f32_plan_never_promotes_under_x64(topo):
    """TPU-compat invariant (found on hardware: "Element type C128 is
    not supported on TPU"): under jax_enable_x64 — which the test env
    and bench enable — an f32 plan's traced programs must contain NO
    f64/c128 values.  Promotion vectors pinned here: jnp.fft's norm=
    scale factor, default-f64 wavenumbers, bare jnp.zeros."""
    from pencilarrays_tpu import PencilFFTPlan

    shape = (8, 6, 10)
    for norm in ("backward", "ortho", "forward", "none"):
        plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float32,
                             normalization=norm)
        x = PencilArray.zeros(plan.input_pencil, (), jnp.float32)
        bad = {"float64", "complex128"} & _jaxpr_dtypes(
            lambda d: plan.forward(
                PencilArray(plan.input_pencil, d)).data, x.data)
        assert not bad, f"norm={norm} promotes to {bad}"
        assert plan.dtype_real == jnp.float32
        for k in plan.wavenumbers():
            assert k.dtype == jnp.float32


def test_f32_ns_model_never_promotes_under_x64(topo):
    from pencilarrays_tpu.models import NavierStokesSpectral, taylor_green

    model = NavierStokesSpectral(topo, 8, viscosity=1e-2,
                                 dtype=jnp.float32)
    uh = taylor_green(model)
    assert uh.data.dtype == jnp.complex64
    bad = {"float64", "complex128"} & _jaxpr_dtypes(
        lambda d: model.step(
            PencilArray(uh.pencil, d, (3,)), 1e-3).data, uh.data)
    assert not bad, f"NS step promotes to {bad}"


def test_from_global_downcast_warns(topo):
    """The deliberate dtype-downcast warning (``from_global`` storing a
    narrower dtype than the input) must actually fire — it is on the
    suite-wide ignore list (pyproject ``filterwarnings``), so this
    dedicated assertion is what keeps it from silently disappearing."""
    pen = Pencil(topo, (8, 8), (0, 1))
    # the suite runs with x64 enabled, so downcasting must be provoked
    # by temporarily disabling it: the f64 input is then stored f32
    # (jax.enable_x64 moved out of jax.experimental across versions)
    enable_x64 = getattr(jax, "enable_x64", None)
    if enable_x64 is None:
        from jax.experimental import enable_x64
    with enable_x64(False), pytest.warns(UserWarning,
                                         match="stored as"):
        PencilArray.from_global(pen, np.zeros((8, 8), np.float64))
