"""Dtype genericity sweep — the analog of the reference's backend-
genericity tests (``test/array_types.jl``): the whole pipeline (construct,
transpose both methods, reduce, gather) must work for every element type
the hardware path supports, with bit-exact data movement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    gather,
    transpose,
)
from pencilarrays_tpu import ops

DTYPES = [
    jnp.float32,
    jnp.float64,
    jnp.float16,
    jnp.bfloat16,
    jnp.complex64,
    jnp.complex128,
    jnp.int32,
    jnp.int64,
    jnp.int16,
    jnp.uint8,
    jnp.bool_,
]


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def sample(shape, dtype):
    rng = np.random.default_rng(0)
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return rng.integers(0, 2, shape).astype(bool)
    if np.issubdtype(dt, np.complexfloating):
        return (rng.standard_normal(shape)
                + 1j * rng.standard_normal(shape)).astype(dt)
    if np.issubdtype(dt, np.integer):
        info = np.iinfo(dt)
        return rng.integers(max(info.min, -100), min(info.max, 100),
                            shape).astype(dt)
    return rng.standard_normal(shape).astype(dt)


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: np.dtype(d).name)
def test_transpose_roundtrip_every_dtype(topo, dtype):
    shape = (10, 11, 12)
    u = sample(shape, dtype)
    pen_a = Pencil(topo, shape, (1, 2), permutation=Permutation(2, 0, 1))
    pen_b = Pencil(topo, shape, (0, 2))
    x = PencilArray.from_global(pen_a, u)
    assert x.dtype == np.dtype(dtype)
    for method in (AllToAll(), Gspmd()):
        y = transpose(x, pen_b, method=method)
        back = transpose(y, pen_a, method=method)
        got = gather(back)
        if np.dtype(dtype).name == "bfloat16":
            np.testing.assert_array_equal(got.view(np.uint16),
                                          u.view(np.uint16))
        else:
            np.testing.assert_array_equal(got, u)


@pytest.mark.parametrize("dtype", [jnp.float16, jnp.bfloat16, jnp.int16,
                                   jnp.uint8],
                         ids=lambda d: np.dtype(d).name)
def test_reductions_narrow_dtypes(topo, dtype):
    shape = (9, 11, 13)  # ragged: masking must hold for narrow types too
    u = sample(shape, dtype)
    pen = Pencil(topo, shape, (1, 2))
    x = PencilArray.from_global(pen, u)
    assert float(ops.maximum(x)) == pytest.approx(float(u.max()))
    assert float(ops.minimum(x)) == pytest.approx(float(u.min()))


def test_bool_any_all_ragged(topo):
    shape = (9, 11, 13)
    pen = Pencil(topo, shape, (1, 2))
    u = np.ones(shape, dtype=bool)
    x = PencilArray.from_global(pen, u)
    assert bool(ops.all(x))  # padding False must be masked
    u2 = np.zeros(shape, dtype=bool)
    u2[8, 10, 12] = True
    assert bool(ops.any(PencilArray.from_global(pen, u2)))
