"""Per-mesh task-graph executor (engine/): ordered dispatch, host
overlap, typed failure scoping, RuntimeConfig snapshots, elastic
drain-and-rebuild.

The contracts under test (ISSUE 12 acceptance):

* **ordering torture** — N producer threads enqueue mixed FFT /
  reshard / probe work concurrently; device-issue order equals enqueue
  order (the SPMD invariant, by construction) and
  ``analysis.spmd.verify_dispatch_log`` certifies the issued trace ==
  the serialized ``collective_costs`` schedule, op-for-op;
* **failure scoping** — a worker-pool exception propagates as a typed
  ``EngineTaskError`` on ITS future and the queue drains on; a
  ``guarded_step`` riding the engine is never wedged;
* **RuntimeConfig** — every knob parsed once, late-arming preserved at
  ``current()``, an Engine's snapshot frozen at construction;
* **host overlap** — a step's ``pack`` stage runs concurrently with
  the previous step's dispatch (the double-buffered pipeline);
* **elastic integration** — ``reform()`` quiesces the engine before
  membership change, the reformed mesh gets a fresh engine generation,
  and held queue entries fail typed ``EngineReformedError``.
"""

import threading
import time

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import engine as eng_mod
from pencilarrays_tpu import guard, obs
from pencilarrays_tpu.analysis import spmd
from pencilarrays_tpu.analysis.errors import DispatchOrderError
from pencilarrays_tpu.engine import (
    DispatchRecord,
    Engine,
    EngineClosedError,
    EngineReformedError,
    EngineTaskError,
    RuntimeConfig,
    get_engine,
)
from pencilarrays_tpu.engine import config as eng_config
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.resilience import faults

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (obs.ENV_VAR, guard.ENV_VAR, faults.ENV_VAR,
                "PENCILARRAYS_TPU_ELASTIC", eng_config.ENGINE_WORKERS_VAR):
        monkeypatch.delenv(var, raising=False)
    guard._reset_for_tests()
    obs_events._reset_for_tests()
    yield
    guard._reset_for_tests()
    obs_events._reset_for_tests()


def _topo2(devices):
    return pa.Topology((2,), devices=devices[:2])


# ---------------------------------------------------------------------------
# ordering: the tentpole invariant
# ---------------------------------------------------------------------------


def test_ordering_torture_mixed_producers(devices):
    """8 producer threads enqueue mixed FFT / reshard / probe work;
    issue order == enqueue order and the dispatched FFT programs
    certify against their collective_costs predictions."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    pen_in = plan.input_pencil
    dest = pa.Pencil(topo, (8, 6, 4), (0,))
    rng = np.random.default_rng(0)
    host = (rng.standard_normal((8, 6, 4))
            + 1j * rng.standard_normal((8, 6, 4))).astype(np.complex64)
    u = pa.PencilArray.from_global(pen_in, host)

    # warm the executables OUTSIDE the torture (compile time would
    # serialize the first dispatch of each kind anyway)
    plan.forward(u)
    pa.reshard(u, dest)

    engine = Engine("torture", workers=4)
    futs, errs = [], []

    def producer(k):
        try:
            for i in range(6):
                kind = (k + i) % 3
                if kind == 0:
                    futs.append(engine.submit(
                        lambda: plan.forward(u),
                        label=f"fft:{k}:{i}",
                        meta={"plan": plan, "direction": "forward",
                              "extra_dims": ()}))
                elif kind == 1:
                    futs.append(engine.submit(
                        lambda: pa.reshard(u, dest),
                        label=f"reshard:{k}:{i}"))
                else:
                    # probe-style host readback of device data
                    futs.append(engine.submit(
                        lambda: float(np.sum(np.abs(
                            np.asarray(pa.gather(u))))),
                        label=f"probe:{k}:{i}"))
        except Exception as e:   # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=producer, args=(k,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    for f in futs:
        f.result(60)
    log = engine.dispatch_log()
    assert len(log) == 48
    # device-issue order == enqueue order, exactly
    seqs = [r.enqueue_seq for r in log]
    assert seqs == sorted(seqs)
    assert [r.issue_seq for r in log] == list(range(1, 49))
    assert all(r.outcome == "ok" for r in log)
    # the static certification: order + per-dispatch trace == prediction
    report = spmd.verify_dispatch_log(log, source="torture")
    assert report["order_ok"]
    assert report["dispatches"] == 48
    assert report["verified_traces"] == sum(
        1 for r in log if "plan" in r.meta)
    assert report["ops"] > 0
    engine.close()


def test_dispatch_order_error_is_typed_and_names_position():
    rec = [DispatchRecord(enqueue_seq=1, issue_seq=1, label="a",
                          outcome="ok", queued_s=0, run_s=0),
           DispatchRecord(enqueue_seq=3, issue_seq=2, label="b",
                          outcome="ok", queued_s=0, run_s=0),
           DispatchRecord(enqueue_seq=2, issue_seq=3, label="c",
                          outcome="ok", queued_s=0, run_s=0)]
    with pytest.raises(DispatchOrderError) as ei:
        spmd.verify_dispatch_log(rec, source="drill")
    assert ei.value.position == 2
    assert ei.value.label == "c"
    assert ei.value.observed_seq == 2
    # gaps (interleaved other-client traffic) are NOT inversions
    ok = spmd.verify_dispatch_log(
        [rec[0], DispatchRecord(enqueue_seq=7, issue_seq=2, label="g",
                                outcome="ok", queued_s=0, run_s=0)],
        source="drill")
    assert ok["order_ok"]


def test_serve_certify_engine_mode(devices):
    """The first-client loop: serve traffic through the engine, then
    prove the pipelined trace == the serialized schedule (zero
    diffs)."""
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(3)
    engine = Engine("certify", workers=2)
    svc = PlanService(max_batch=4, max_wait_s=0.0, engine=engine)
    for i in range(8):
        svc.submit("t0", (rng.standard_normal((8, 6, 4))
                          + 1j * rng.standard_normal((8, 6, 4))
                          ).astype(np.complex64), plan=plan)
    svc.drain()
    report = svc.certify(engine=True)
    assert report["ok"]
    assert report["engine"]["order_ok"]
    assert report["engine"]["dispatches"] == 2      # 8 reqs / batch 4
    assert report["engine"]["verified_traces"] == 2
    assert report["engine"]["unverified"] == 0
    svc.close()
    engine.close()


# ---------------------------------------------------------------------------
# failure scoping: typed errors, the queue drains on
# ---------------------------------------------------------------------------


def test_worker_pool_exception_typed_and_queue_drains():
    engine = Engine("errs", workers=2)
    before = engine.submit(lambda: "a", label="before")
    bad = engine.submit(lambda x: x, pack=lambda: 1 / 0, label="bad")
    after = [engine.submit(lambda i=i: i, label=f"after{i}")
             for i in range(5)]
    assert before.result(10) == "a"
    # the queue drained PAST the poisoned task
    assert [f.result(10) for f in after] == list(range(5))
    with pytest.raises(EngineTaskError) as ei:
        bad.result(10)
    assert isinstance(ei.value.cause, ZeroDivisionError)
    assert ei.value.stage == "pack"
    assert isinstance(ei.value.__cause__, ZeroDivisionError)
    # the failed dispatch is in the log, typed, in order
    log = engine.dispatch_log()
    assert [r.label for r in log][:2] == ["before", "bad"]
    assert log[1].outcome == "EngineTaskError"
    engine.close()


def test_guarded_step_not_wedged_by_pool_failure(devices):
    """A serve batch whose neighbor engine-task failed still runs its
    guarded_step and resolves its tickets — the regression pin for
    'exception drains the queue rather than wedging guarded_step'."""
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(5)
    engine = Engine("wedge", workers=2)
    engine.submit(lambda x: x, pack=lambda: (_ for _ in ()).throw(
        RuntimeError("poison")), label="poison")
    svc = PlanService(max_batch=2, max_wait_s=0.0, engine=engine)
    t = svc.submit("t", (rng.standard_normal((8, 6, 4))
                         + 1j * rng.standard_normal((8, 6, 4))
                         ).astype(np.complex64), plan=plan)
    svc.drain()
    assert t.result(0) is not None
    svc.close()
    engine.close()


def test_dispatch_error_fails_only_its_future():
    engine = Engine("scope", workers=1)
    bad = engine.submit(lambda: 1 / 0, label="bad-run")
    good = engine.submit(lambda: "fine", label="good")
    assert good.result(10) == "fine"
    with pytest.raises(ZeroDivisionError):
        bad.result(10)
    engine.close()


def test_closed_engine_rejects_typed():
    engine = Engine("closed")
    engine.close()
    with pytest.raises(EngineClosedError):
        engine.submit(lambda: 1)
    with pytest.raises(EngineClosedError):
        engine.host_task(lambda: 1)


# ---------------------------------------------------------------------------
# host overlap: the double-buffered pipeline
# ---------------------------------------------------------------------------


def test_pack_overlaps_previous_dispatch():
    """With pack ~= run, a pipelined K-step chain approaches
    pack + K*run instead of K*(pack + run)."""
    engine = Engine("overlap", workers=2)
    d = 0.08
    t0 = time.perf_counter()
    futs = [engine.submit(lambda _: time.sleep(d),
                          pack=lambda: time.sleep(d),
                          label=f"s{i}") for i in range(4)]
    for f in futs:
        f.result(30)
    wall = time.perf_counter() - t0
    serial = 4 * 2 * d                      # sync-per-dispatch shape
    assert wall < serial * 0.85, (wall, serial)
    st = engine.stats()
    assert st["dispatched"] == 4 and st["host_tasks"] == 4
    engine.close()


def test_host_task_and_timers():
    engine = Engine("host")
    assert engine.host_task(lambda: 41).result(10) == 41
    hits = []
    engine.call_later(0.02, lambda: hits.append(1))
    deadline = time.monotonic() + 5
    while not hits and time.monotonic() < deadline:
        time.sleep(0.01)
    assert hits == [1]
    engine.close()


# ---------------------------------------------------------------------------
# RuntimeConfig: one parser, snapshot-at-construction
# ---------------------------------------------------------------------------


def test_runtime_config_resolves_every_layer(monkeypatch):
    monkeypatch.setenv("PENCILARRAYS_TPU_GUARD_TIMEOUT", "12.5")
    monkeypatch.setenv("PENCILARRAYS_TPU_CLUSTER_LEASE_TTL", "3.5")
    monkeypatch.setenv("PENCILARRAYS_TPU_ELASTIC_ROUNDS", "4")
    monkeypatch.setenv("PENCILARRAYS_TPU_OBS_AGG_S", "2.5")
    monkeypatch.setenv(eng_config.ENGINE_WORKERS_VAR, "3")
    cfg = RuntimeConfig.resolve()
    assert cfg.guard_timeout == 12.5
    assert cfg.lease_ttl == 3.5
    assert cfg.elastic_rounds == 4
    assert cfg.obs_agg_cadence == 2.5
    assert cfg.engine_workers == 3
    # malformed values keep each knob's documented default
    monkeypatch.setenv("PENCILARRAYS_TPU_GUARD_TIMEOUT", "nan-ish")
    monkeypatch.setenv("PENCILARRAYS_TPU_ELASTIC_ROUNDS", "zero")
    cfg = RuntimeConfig.resolve()
    assert cfg.guard_timeout == 300.0
    assert cfg.elastic_rounds == 8


def test_layer_accessors_delegate_and_late_arm(monkeypatch):
    from pencilarrays_tpu import cluster
    from pencilarrays_tpu.cluster import elastic

    monkeypatch.setenv("PENCILARRAYS_TPU_GUARD_TIMEOUT", "7")
    assert guard.hang_timeout() == 7.0
    # late-arming: the env change is visible at the NEXT probe
    monkeypatch.setenv("PENCILARRAYS_TPU_GUARD_TIMEOUT", "9")
    assert guard.hang_timeout() == 9.0
    monkeypatch.setenv("PENCILARRAYS_TPU_CLUSTER_RANK", "5")
    assert cluster.rank() == 5
    monkeypatch.setenv("PENCILARRAYS_TPU_ELASTIC", "1")
    assert elastic.enabled()
    monkeypatch.delenv("PENCILARRAYS_TPU_ELASTIC")
    assert not elastic.enabled()
    monkeypatch.setenv(guard.ENV_VAR, "1")
    assert guard.enabled()
    monkeypatch.delenv(guard.ENV_VAR)
    assert not guard.enabled()


def test_env_key_fast_path_sees_every_mutation(monkeypatch):
    """PR 18: ``current()`` is the gate probe under ``obs.enabled()``
    on per-dispatch hot paths, so its change-detection key is built
    from exception-free backing-dict probes instead of 27
    ``os.environ.get`` KeyError round-trips.  The fast path must see
    set, CHANGE, and delete for every watched var — a stale key here
    silently breaks late arming for a whole subsystem."""
    for var in eng_config.WATCHED_VARS:
        monkeypatch.delenv(var, raising=False)  # normalize: unset
        before = eng_config._env_key()
        monkeypatch.setenv(var, "_pin_a")
        a = eng_config._env_key()
        assert a != before, f"{var}: set invisible to the fast path"
        monkeypatch.setenv(var, "_pin_b")
        b = eng_config._env_key()
        assert b != a, f"{var}: change invisible to the fast path"
        monkeypatch.delenv(var)
        assert eng_config._env_key() == before, \
            f"{var}: delete invisible to the fast path"
    # and the snapshot itself re-resolves through the fast-path key
    monkeypatch.setenv("PENCILARRAYS_TPU_OBS", "1")
    assert eng_config.current().obs_on
    monkeypatch.delenv("PENCILARRAYS_TPU_OBS")
    assert not eng_config.current().obs_on


def test_engine_snapshot_frozen_at_construction(monkeypatch):
    monkeypatch.setenv("PENCILARRAYS_TPU_GUARD_TIMEOUT", "11")
    engine = Engine("frozen")
    assert engine.config.guard_timeout == 11.0
    monkeypatch.setenv("PENCILARRAYS_TPU_GUARD_TIMEOUT", "22")
    # the process-global snapshot follows...
    assert eng_config.current().guard_timeout == 22.0
    # ...but the engine's does NOT until an explicit reform
    assert engine.config.guard_timeout == 11.0
    engine.reform()
    assert engine.config.guard_timeout == 22.0
    assert engine.generation == 1
    engine.close()


# ---------------------------------------------------------------------------
# streaming serve (no daemon thread) + elastic reformation
# ---------------------------------------------------------------------------


def test_serve_streaming_without_daemon_thread(devices):
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(7)
    n_before = threading.active_count()
    engine = Engine("stream")
    svc = PlanService(max_batch=4, max_wait_s=0.001, engine=engine)
    svc.start()
    tickets = [svc.submit("t", (rng.standard_normal((8, 6, 4))
                                + 1j * rng.standard_normal((8, 6, 4))
                                ).astype(np.complex64), plan=plan)
               for _ in range(6)]
    outs = [t.result(60) for t in tickets]       # no drain() call
    assert all(o is not None for o in outs)
    # a request landing on an IDLE streaming service must still be
    # dispatched: the idle tick does not reschedule itself, so every
    # admission re-arms the pump (regression pin — this wedged forever
    # when only start() scheduled the tick)
    time.sleep(0.05)                             # let the armed tick die
    late = svc.submit("t", (rng.standard_normal((8, 6, 4))
                            + 1j * rng.standard_normal((8, 6, 4))
                            ).astype(np.complex64), plan=plan)
    assert late.result(60) is not None
    svc.stop()
    # no pa-serve-dispatch polling daemon exists anymore: the only new
    # threads are the engine's own consumer/pool (<= 1 + workers)
    assert threading.active_count() <= n_before + 1 + engine.stats()[
        "workers"]
    assert all(t.name.startswith("pa-engine-stream")
               for t in threading.enumerate()
               if t.name.startswith("pa-") and "stream" in t.name)
    svc.close()
    engine.close()


def test_streaming_rearms_after_engine_reform(devices):
    """The armed pump tick dies with a reform (timers are dropped);
    the generation-tagged dedup must notice, or every later admission
    no-ops at the duplicate check and streaming wedges forever
    (regression pin)."""
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(13)
    engine = Engine("re-stream")
    svc = PlanService(max_batch=4, max_wait_s=0.05, engine=engine)
    svc.start()                 # arms a tick the reform will drop
    engine.reform()
    t = svc.submit("t", (rng.standard_normal((8, 6, 4))
                         + 1j * rng.standard_normal((8, 6, 4))
                         ).astype(np.complex64), plan=plan)
    assert t.result(60) is not None
    svc.stop()
    svc.close()
    engine.close()


def test_streaming_queued_traffic_drains_after_reform(devices):
    """A request queued BEFORE the reform must drain afterwards even
    if no further admission ever arrives: the engine's post-reform
    hook re-arms the pump (the admission-path token check alone only
    recovered on the NEXT submit — regression pin)."""
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(19)
    engine = Engine("re-queued")
    svc = PlanService(max_batch=4, max_wait_s=0.2, engine=engine)
    svc.start()
    t = svc.submit("t", (rng.standard_normal((8, 6, 4))
                         + 1j * rng.standard_normal((8, 6, 4))
                         ).astype(np.complex64), plan=plan)
    engine.reform()     # drops the armed tick before its deadline
    assert t.result(60) is not None     # NO further submit
    svc.stop()
    svc.close()
    # the service unhooks at close: a shared long-lived engine must
    # not accumulate dead services' reform callbacks
    assert not engine._reform_cbs
    engine.close()


def test_streaming_full_batch_dispatches_before_deadline(devices):
    """A full coalesce group gains nothing by waiting: the admission
    that completes the batch ticks at the minimum spacing instead of
    the coalescing deadline (regression pin — full batches used to
    wait out the whole max_wait_s window)."""
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(29)

    def payload():
        return (rng.standard_normal((8, 6, 4))
                + 1j * rng.standard_normal((8, 6, 4))
                ).astype(np.complex64)

    engine = Engine("fullfast")
    svc = PlanService(max_batch=2, max_wait_s=5.0, engine=engine)
    # warm the B=2 coalesced executable OUTSIDE the timed window
    for tk in [svc.submit("t", payload(), plan=plan) for _ in range(2)]:
        pass
    svc.drain()
    svc.start()
    t0 = time.monotonic()
    tickets = [svc.submit("t", payload(), plan=plan) for _ in range(2)]
    for tk in tickets:
        assert tk.result(30) is not None
    assert time.monotonic() - t0 < 2.5      # far below max_wait_s=5
    svc.stop()
    svc.close()
    engine.close()


def test_streaming_quiesced_admission_drains_on_resume(devices):
    """A request admitted while the engine is quiesced arms no tick
    (accepting is False); a FAILED reformation resumes the engine
    without reforming it, so resume() must run the re-arm hooks too —
    otherwise the queued request waits for unrelated future traffic
    (regression pin)."""
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(23)
    engine = Engine("re-resume")
    svc = PlanService(max_batch=4, max_wait_s=0.01, engine=engine)
    svc.start()
    assert engine.quiesce(5)
    t = svc.submit("t", (rng.standard_normal((8, 6, 4))
                         + 1j * rng.standard_normal((8, 6, 4))
                         ).astype(np.complex64), plan=plan)
    engine.resume()     # the failed-reformation path
    assert t.result(60) is not None     # NO further submit
    svc.stop()
    svc.close()
    engine.close()


def test_step_fails_tickets_when_submission_fails(devices):
    """Once a batch left the admission queue its tickets are the
    service's to resolve: a submission failure (engine closed between
    take_ready and submit) fails THAT batch typed and still submits /
    fails the remaining taken batches — never strands a waiter
    (regression pin)."""
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(17)
    engine = Engine("strand")
    svc = PlanService(max_batch=4, max_wait_s=0.0, engine=engine)
    host = (rng.standard_normal((8, 6, 4))
            + 1j * rng.standard_normal((8, 6, 4))).astype(np.complex64)
    fwd = svc.submit("t", host, plan=plan)
    bwd = svc.submit("t", host, plan=plan, direction="backward")
    engine.close()
    # two keys -> two batches; BOTH are taken and both fail typed
    assert svc.step(flush=True) == 2
    for tk in (fwd, bwd):
        with pytest.raises(EngineClosedError):
            tk.result(0)
    svc.close()


def test_stale_generation_dispatch_skips_log():
    """A quiesce-timeout survivor finishing after a reform must not
    append its old (lower) enqueue_seq behind new-generation records —
    that made verify_dispatch_log raise a spurious DispatchOrderError
    on a healthy engine (regression pin).  Its future still resolves
    and the engine is not left busy."""
    engine = Engine("stale", workers=1)
    started, release = threading.Event(), threading.Event()

    def slow():
        started.set()
        release.wait(30)
        return "slow"

    f_old = engine.submit(slow, label="old-gen")
    assert started.wait(10)
    engine.reform(timeout=0.05)     # quiesce times out on the stuck
    # dispatch; reform writes it off and proceeds
    f_new = engine.submit(lambda: "new", label="new-gen")
    assert f_new.result(10) == "new"
    release.set()
    assert f_old.result(10) == "slow"
    assert [r.label for r in engine.dispatch_log()] == ["new-gen"]
    assert spmd.verify_dispatch_log(
        engine.dispatch_log(), source="stale")["order_ok"]
    assert not engine.stats()["busy"]
    engine.close()


def test_quiesce_waits_for_mid_flight_timer():
    """A firing timer tick is in-flight work: a streaming pump mid-
    tick submits dispatches, so quiesce() must wait it out exactly
    like a run-stage dispatch (regression pin — timer work used to be
    invisible to quiesce, letting a reformation proceed under a
    running tick)."""
    engine = Engine("timerbusy")
    started, release = threading.Event(), threading.Event()

    def tick():
        started.set()
        release.wait(10)

    engine.call_later(0.0, tick)
    assert started.wait(10)
    assert not engine.quiesce(0.2)      # tick mid-flight: times out
    release.set()
    assert engine.quiesce(10)           # tick done: quiesce completes
    engine.resume()
    engine.close()


def test_dispatch_log_meta_is_a_snapshot():
    """The logged meta is certification history: mutating the caller's
    dict after the dispatch completes must not rewrite it."""
    engine = Engine("snap")
    meta = {"k": 1}
    engine.submit(lambda: None, label="m", meta=meta).result(10)
    meta["k"] = 2
    rec = engine.dispatch_log()[-1]
    assert rec.meta == {"k": 1}
    assert rec.meta is not meta
    engine.close()


def test_reform_fails_held_dispatches_typed():
    engine = Engine("held")
    assert engine.quiesce(5)
    held = engine.submit(lambda: "never", label="held")
    engine.reform()
    with pytest.raises(EngineReformedError) as ei:
        held.result(10)
    assert ei.value.generation == 1
    # the reformed generation dispatches immediately
    assert engine.submit(lambda: "alive").result(10) == "alive"
    engine.close()


def test_elastic_reform_rebuilds_engine(devices, tmp_path):
    """The drill pin: elastic.reform() quiesces the engines before
    membership consensus, and the reindexed coordinator gets a fresh
    engine generation that still serves (the MTTR-test shape, engine
    edition)."""
    from pencilarrays_tpu import cluster
    from pencilarrays_tpu.cluster import elastic
    from pencilarrays_tpu.cluster.consensus import Coordinator
    from pencilarrays_tpu.cluster.kv import FileKV
    from pencilarrays_tpu.serve import PlanService

    topo = _topo2(devices)
    rng = np.random.default_rng(11)

    def payload():
        return (rng.standard_normal((8, 6, 4))
                + 1j * rng.standard_normal((8, 6, 4))
                ).astype(np.complex64)

    engine = get_engine()       # the shared engine reform_all touches
    gen0 = engine.generation
    svc = PlanService(max_batch=2, max_wait_s=0.0)
    svc.register_plan("drill", lambda ctx: PencilFFTPlan(topo, (8, 6, 4)))
    t0 = svc.submit("t", payload(), name="drill")
    svc.drain()
    assert t0.result(0) is not None
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 1, lease_ttl=5.0, verdict_timeout=20)
    try:
        r = elastic.reform(c0, reason="resize", install=False)
        assert engine.generation == gen0 + 1
        assert "engine_quiesce_s" in r.timings
        # the reformed engine serves: queued admission traffic rebinds
        # to the factory-rebuilt plan and drains through the fresh
        # generation
        t1 = svc.submit("t", payload(), name="drill")
        svc.drain()
        assert t1.result(0) is not None
        r.coordinator.shutdown()
    finally:
        svc.close()
        cluster._reset_for_tests()


def test_exec_bench_smoke(devices, tmp_path):
    """The BENCH_EXEC harness runs end to end at toy scale: both arms
    measured, the dispatch log certified (zero trace diffs), the HLO
    pin proved.  The >=1.2x headline is the committed full-scale
    artifact's claim, not this smoke's — a 1-core CI box's thread
    scheduling is not a benchmark."""
    from benchmarks.exec_bench import run_exec_suite

    res = run_exec_suite(devices[:2], shape=(8, 6, 4), n_steps=4,
                         batch=2, repeats=1, workdir=str(tmp_path))
    assert res["sync"]["steps_per_s"] > 0
    assert res["pipelined"]["steps_per_s"] > 0
    assert res["speedup"] == pytest.approx(
        res["pipelined"]["steps_per_s"] / res["sync"]["steps_per_s"])
    assert 0.0 <= res["host_overlap_fraction"] <= 1.0
    pin = res["hlo_pin"]
    assert pin["predicted_equals_hlo"], pin
    assert pin["dispatch_log"]["order_ok"]
    assert pin["dispatch_log"]["trace_diffs"] == 0
    assert pin["dispatch_log"]["dispatches"] == 4
    assert pin["dispatch_log"]["unverified"] == 0


def test_spawn_thread_inventory():
    from pencilarrays_tpu.engine.threads import spawned

    engine = Engine("inv")
    engine.submit(lambda: None).result(10)
    names = spawned()
    assert any(n.startswith("pa-engine-inv-dispatch") for n in names)
    engine.close()


# ---------------------------------------------------------------------------
# ISSUE 14 satellite: native step-loop pipelining (models + checkpoint)
# ---------------------------------------------------------------------------


def test_run_steps_async_overlaps_checkpoint_saves(devices, tmp_path):
    """``run_steps_async`` drives a model step loop through the ordered
    dispatch queue with host-pool checkpoint serialization: results are
    bit-identical to the sync loop, every requested checkpoint commits,
    and the saves ran on the HOST pool (engine stats), not the consumer
    — no caller-side future plumbing."""
    from pencilarrays_tpu.models.diffusion import DiffusionSpectral
    from pencilarrays_tpu.resilience.checkpoint import CheckpointManager

    topo = pa.Topology((2,), devices=devices[:2])
    model = DiffusionSpectral(topo, (8, 6, 4))
    rng = np.random.default_rng(7)
    u0 = pa.PencilArray.from_global(
        model.plan.input_pencil,
        rng.standard_normal((8, 6, 4)).astype(np.float32))
    uh = model.from_physical(u0)
    engine = Engine("pipe-test")
    try:
        ck = CheckpointManager(str(tmp_path / "ck"))
        before = engine.stats()["host_tasks"]
        pipe = model.run_async(uh, 0.01, 5, engine=engine,
                               checkpoint=ck, checkpoint_every=2)
        final = pipe.result(60)
        assert len(pipe.saves) == 2
        assert ck.steps() == [2, 4]
        assert engine.stats()["host_tasks"] - before >= 2
        ref = uh
        for _ in range(5):
            ref = model.step(ref, 0.01)
        np.testing.assert_array_equal(
            np.asarray(pa.gather(final)), np.asarray(pa.gather(ref)))
        # the serialized state is the step it names: restoring step 2
        # equals the 2-step sync state
        restored = ck.restore(2).read("uh", model.plan.output_pencil)
        ref2 = model.step(model.step(uh, 0.01), 0.01)
        np.testing.assert_array_equal(
            np.asarray(pa.gather(restored)),
            np.asarray(pa.gather(ref2)))
    finally:
        engine.close()


def test_save_async_runs_on_host_pool(devices, tmp_path):
    from pencilarrays_tpu.resilience.checkpoint import CheckpointManager

    topo = _topo2(devices)
    pen = pa.Pencil(topo, (8, 6, 4), (0,))
    x = pa.PencilArray.from_global(
        pen, np.arange(192, dtype=np.float32).reshape(8, 6, 4))
    engine = Engine("save-async")
    try:
        ck = CheckpointManager(str(tmp_path / "ck"))
        fut = ck.save_async(3, {"u": x}, engine=engine)
        path = fut.result(60)
        assert path.endswith("step-00000003")
        assert ck.steps() == [3]
        assert engine.stats()["host_tasks"] >= 1
    finally:
        engine.close()


def test_models_step_async_matches_sync(devices):
    from pencilarrays_tpu.models.spectral import (NavierStokesSpectral,
                                                  taylor_green)

    topo = pa.Topology((2, 2), devices=devices[:4])
    model = NavierStokesSpectral(topo, 8)
    uh = taylor_green(model)
    engine = Engine("ns-async")
    try:
        fut = model.step_async(uh, 1e-3, engine=engine)
        out = fut.result(120)
        ref = model.step(uh, 1e-3)
        np.testing.assert_array_equal(
            np.asarray(pa.gather(out)), np.asarray(pa.gather(ref)))
    finally:
        engine.close()


def test_run_steps_async_propagates_step_failure(devices):
    """A stepper failure at step k must reach the pipeline's final
    future — later steps refuse to advance the stale state (review
    finding: the old loop silently returned a short-count state)."""
    from pencilarrays_tpu.engine import run_steps_async

    calls = {"n": 0}

    class Boom(RuntimeError):
        pass

    def stepper(s):
        calls["n"] += 1
        if calls["n"] == 3:
            raise Boom("step 3 dies")
        return s + 1

    engine = Engine("fail-prop")
    try:
        pipe = run_steps_async(stepper, 0, 5, engine=engine)
        with pytest.raises(Boom):
            pipe.result(60)
        # the stepper never advanced past the failure
        assert calls["n"] == 3
    finally:
        engine.close()
