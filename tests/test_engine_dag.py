"""Engine v2 task DAG (ISSUE 16): out-of-order issue on disjoint
resource chains, SLO priority lanes with a starvation bound, explicit
``after=`` edges, and the partial-order dispatch-log certification.

Two halves:

* **adversarial certification fixtures** — hand-built dispatch logs
  fed straight to ``analysis.spmd.verify_dispatch_log``: an in-chain
  inversion is fatal (typed ``DispatchOrderError`` naming the violated
  chain edge), a cross-chain reorder certifies clean (and is counted),
  a forged resource set — dispatched plan not declared in ``writes`` —
  is caught, a barrier can never jump the log, a duplicate enqueue seq
  is typed, and a v1 all-barrier log still verifies in total-order
  mode;
* **live-engine behavior** — disjoint chains issue out of order, lanes
  bias the pick among ready tasks, ``starve_s`` bounds the bypass
  (a starved task issues next REGARDLESS of lane), ``after=`` pins
  cross-chain order and refuses cross-engine edges, ``dag=False`` (and
  the ``PENCILARRAYS_TPU_ENGINE_DAG=0`` escape hatch) keep the exact
  v1 total order, and a reform drops held lanes typed.
"""

import threading
import time

import pytest

from pencilarrays_tpu import guard, obs
from pencilarrays_tpu.analysis import spmd
from pencilarrays_tpu.analysis.errors import (
    DispatchOrderError,
    ScheduleMismatchError,
)
from pencilarrays_tpu.engine import (
    DispatchRecord,
    Engine,
    EngineReformedError,
)
from pencilarrays_tpu.engine import config as eng_config
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (obs.ENV_VAR, guard.ENV_VAR, faults.ENV_VAR,
                eng_config.ENGINE_WORKERS_VAR, eng_config.ENGINE_DAG_VAR,
                eng_config.ENGINE_STARVE_VAR):
        monkeypatch.delenv(var, raising=False)
    obs_events._reset_for_tests()
    yield
    obs_events._reset_for_tests()


# ---------------------------------------------------------------------------
# adversarial certification fixtures
# ---------------------------------------------------------------------------


def _rec(enqueue_seq, issue_seq, label, **kw):
    kw.setdefault("outcome", "ok")
    return DispatchRecord(enqueue_seq=enqueue_seq, issue_seq=issue_seq,
                          label=label, queued_s=0.0, run_s=0.0,
                          outcome=kw.pop("outcome"), **kw)


def _chain_rec(enqueue_seq, issue_seq, label, res, deps=()):
    return _rec(enqueue_seq, issue_seq, label, barrier=False,
                chain=res, writes=(res,), deps=tuple(deps))


def test_v1_total_order_log_still_verifies():
    # an all-barrier log (every pre-v2 engine, every old pickle) takes
    # the total-order path: strictly ascending passes, an inversion is
    # the same typed error PR 12 pinned
    log = [_rec(i, i, f"s{i}") for i in range(1, 5)]
    out = spmd.verify_dispatch_log(log, source="t", verify_traces=False)
    assert out["mode"] == "total"
    assert out["order_ok"] and out["dispatches"] == 4
    bad = [log[0], log[2], log[1], log[3]]
    with pytest.raises(DispatchOrderError) as ei:
        spmd.verify_dispatch_log(bad, source="t", verify_traces=False)
    assert ei.value.position == 2


def test_cross_chain_reorder_certifies_clean():
    # chains a and b are disjoint: b1 issuing before a1 is the whole
    # POINT of v2 — certified clean, counted in "reordered"
    log = [
        _chain_rec(2, 1, "b1", "b"),
        _chain_rec(1, 2, "a1", "a"),
        _chain_rec(3, 3, "a2", "a", deps=(1,)),
    ]
    out = spmd.verify_dispatch_log(log, source="t", verify_traces=False)
    assert out["mode"] == "partial"
    assert out["order_ok"]
    assert out["chains"] == 2
    assert out["reordered"] == 1


def test_in_chain_inversion_is_fatal():
    # a2 issued before a1 on the SAME chain: the SPMD collective-order
    # invariant is broken — typed, naming the violated edge
    log = [
        _chain_rec(2, 1, "a2", "a", deps=(1,)),
        _chain_rec(1, 2, "a1", "a"),
    ]
    with pytest.raises(DispatchOrderError) as ei:
        spmd.verify_dispatch_log(log, source="t", verify_traces=False)
    assert ei.value.chain == "a"
    assert ei.value.dep_seq == 1
    assert ei.value.observed_seq == 2


def test_recomputed_edges_catch_undeclared_deps():
    # the verifier RECOMPUTES hazards from the declared resource sets —
    # a log whose recorded deps were scrubbed still fails on the
    # recomputed WAW edge
    log = [
        _chain_rec(2, 1, "a2", "a"),        # deps forged empty
        _chain_rec(1, 2, "a1", "a"),
    ]
    with pytest.raises(DispatchOrderError):
        spmd.verify_dispatch_log(log, source="t", verify_traces=False)


def test_barrier_cannot_jump_the_log():
    # a barrier conflicts with EVERYTHING: chain work enqueued after it
    # issuing before it is fatal even though the chains are disjoint
    log = [
        _chain_rec(1, 1, "a1", "a"),
        _chain_rec(3, 2, "a2", "a", deps=(1, 2)),
        _rec(2, 3, "bar"),                  # barrier issued LAST
    ]
    with pytest.raises(DispatchOrderError) as ei:
        spmd.verify_dispatch_log(log, source="t", verify_traces=False)
    assert ei.value.chain == "*"


def test_duplicate_enqueue_seq_is_typed():
    log = [
        _chain_rec(1, 1, "a1", "a"),
        _chain_rec(1, 2, "dup", "b"),
    ]
    with pytest.raises(DispatchOrderError):
        spmd.verify_dispatch_log(log, source="t", verify_traces=False)


class _StubPlan:
    def plan_key(self):
        return "feedc0de"


def test_forged_resource_set_is_caught():
    # a non-barrier record that DISPATCHED a plan but never declared
    # the matching plan:<fp> write lied about its chain membership —
    # the partial-order proof above it proved the wrong graph
    forged = _rec(1, 1, "fft", barrier=False, chain="route:x",
                  writes=("route:x",), meta={"plan": _StubPlan()})
    with pytest.raises(ScheduleMismatchError) as ei:
        spmd.verify_dispatch_log([forged], source="t",
                                 verify_traces=False)
    assert "resource-set" in str(ei.value)
    honest = _rec(1, 1, "fft", barrier=False, chain="plan:feedc0de",
                  writes=("plan:feedc0de",), meta={"plan": _StubPlan()})
    out = spmd.verify_dispatch_log([honest], source="t",
                                   verify_traces=False)
    assert out["order_ok"] and out["mode"] == "partial"


# ---------------------------------------------------------------------------
# live-engine behavior
# ---------------------------------------------------------------------------


def _labels(engine):
    return [r.label for r in engine.dispatch_log()]


def test_disjoint_chains_issue_out_of_order():
    # a1 holds the consumer; by completion a2 (chain a) and b (chain b,
    # lane 1) are both queued — b is ready and outranks a2, so it
    # issues first despite the later enqueue seq
    e = Engine("dag-ooo", workers=2)
    try:
        assert e.dag
        fa1 = e.submit(lambda: time.sleep(0.15), label="a1",
                       writes=("a",))
        fa2 = e.submit(lambda: None, label="a2", writes=("a",))
        fb = e.submit(lambda: None, label="b", writes=("b",), lane=1)
        for f in (fa1, fa2, fb):
            f.result(30)
        assert e.drain(30)
        labels = _labels(e)
        assert labels.index("b") < labels.index("a2")
        assert labels.index("a1") < labels.index("a2")
        st = e.stats()
        assert st["out_of_order"] >= 1
        cert = spmd.verify_dispatch_log(e.dispatch_log(),
                                        source="dag-ooo")
        assert cert["mode"] == "partial"
        assert cert["order_ok"] and cert["reordered"] >= 1
    finally:
        e.close()


def test_lane_bias_picks_high_lane_first():
    # behind a plug barrier, a whale chain and one lane-1 minnow all
    # become ready at once: the minnow issues immediately after the
    # plug, ahead of every whale enqueued before it
    e = Engine("dag-lane", workers=2, starve_s=30.0)
    try:
        plug = e.submit(lambda: time.sleep(0.25), label="plug")
        whales = [e.submit(lambda: None, label=f"w{i}",
                           writes=("plan:whale",)) for i in range(3)]
        minnow = e.submit(lambda: None, label="m", writes=("plan:m",),
                          lane=1)
        for f in [plug, minnow] + whales:
            f.result(30)
        assert e.drain(30)
        assert _labels(e) == ["plug", "m", "w0", "w1", "w2"]
    finally:
        e.close()


def test_starvation_bound_overrides_lanes():
    # starve_s=0 makes every queued task immediately starved: the pick
    # degenerates to strict enqueue order EVEN against a higher lane —
    # the bound guarantees progress >= v1 for any lane mix
    e = Engine("dag-starve", workers=2, starve_s=0.0)
    try:
        plug = e.submit(lambda: time.sleep(0.2), label="plug")
        lo = e.submit(lambda: None, label="lo", writes=("x",))
        hi = e.submit(lambda: None, label="hi", writes=("y",), lane=5)
        for f in (plug, lo, hi):
            f.result(30)
        assert e.drain(30)
        assert _labels(e) == ["plug", "lo", "hi"]
        assert e.stats()["starved_issues"] >= 1
    finally:
        e.close()


def test_after_edges_pin_cross_chain_order():
    # chains a and b are disjoint, so b COULD issue first — the
    # explicit after= edge pins it behind a, and the edge lands in the
    # record's deps so the verifier audits it too
    e = Engine("dag-after", workers=2)
    try:
        fa = e.submit(lambda: time.sleep(0.1), label="a",
                      writes=("a",))
        fb = e.submit(lambda: None, label="b", writes=("b",),
                      lane=1, after=[fa])
        fb.result(30)
        assert e.drain(30)
        labels = _labels(e)
        assert labels.index("a") < labels.index("b")
        rec_b = next(r for r in e.dispatch_log() if r.label == "b")
        assert fa._pa_seq in rec_b.deps
        # and the recorded edge is load-bearing in certification: the
        # same two records with the issue order flipped are fatal
        rec_a = next(r for r in e.dispatch_log() if r.label == "a")
        with pytest.raises(DispatchOrderError):
            spmd.verify_dispatch_log([rec_b, rec_a], source="t",
                                     verify_traces=False)
    finally:
        e.close()


def test_after_refuses_cross_engine_edges():
    e1 = Engine("dag-x1", workers=2)
    e2 = Engine("dag-x2", workers=2)
    try:
        f1 = e1.submit(lambda: None, label="t1", writes=("a",))
        with pytest.raises(ValueError, match="cross-engine"):
            e2.submit(lambda: None, label="t2", writes=("b",),
                      after=[f1])
        f1.result(30)
    finally:
        e1.close()
        e2.close()


def test_dag_off_keeps_total_order(monkeypatch):
    # the multi-controller escape hatch: PENCILARRAYS_TPU_ENGINE_DAG=0
    # makes every task a barrier no matter what it declares — the
    # exact v1 total order, still certifiable in total mode
    monkeypatch.setenv(eng_config.ENGINE_DAG_VAR, "0")
    e = Engine("dag-off", workers=2)
    try:
        assert not e.dag
        futs = [e.submit(lambda: None, label=f"t{i}",
                         writes=("a" if i % 2 else "b",), lane=i % 3)
                for i in range(6)]
        for f in futs:
            f.result(30)
        assert e.drain(30)
        assert _labels(e) == [f"t{i}" for i in range(6)]
        assert all(r.barrier for r in e.dispatch_log())
        assert e.stats()["out_of_order"] == 0
        cert = spmd.verify_dispatch_log(e.dispatch_log(),
                                        source="dag-off")
        assert cert["mode"] == "total" and cert["order_ok"]
    finally:
        e.close()


def test_reform_drops_held_lanes_typed():
    # a reform quiesces the consumer and drops every HELD dispatch
    # typed — including non-barrier DAG tasks parked across lanes —
    # and the fresh generation starts with an empty graph
    e = Engine("dag-reform", workers=2)
    try:
        plug = e.submit(lambda: time.sleep(0.3), label="plug")
        held = [e.submit(lambda: None, label=f"h{i}",
                         writes=("a",), lane=i % 2) for i in range(4)]
        time.sleep(0.05)            # plug is in flight, h* are held
        e.reform()
        plug.result(30)             # in-flight work finishes
        for f in held:
            with pytest.raises(EngineReformedError):
                f.result(30)
        st = e.stats()
        assert st["queued"] == 0 and st["ready"] == 0
        assert not st["lanes"]
        f2 = e.submit(lambda: 7, label="fresh", writes=("a",))
        assert f2.result(30) == 7
    finally:
        e.close()


def test_lane_gauges_emitted(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    obs_events._reset_for_tests()
    e = Engine("dag-gauge", workers=2)
    try:
        fa = e.submit(lambda: None, label="a", writes=("a",))
        fb = e.submit(lambda: None, label="b", writes=("b",), lane=2)
        fa.result(30)
        fb.result(30)
        assert e.drain(30)
        gauges = obs.snapshot()["gauges"]
        assert any(k.startswith("engine.lanes{") and "lane=2" in k
                   for k in gauges), gauges
        assert any(k.startswith("engine.ready_tasks{")
                   for k in gauges), gauges
    finally:
        e.close()


# ---------------------------------------------------------------------------
# bench arms (smoke)
# ---------------------------------------------------------------------------


def test_depth_stress_smoke():
    from benchmarks.exec_bench import run_depth_stress

    res = run_depth_stress(depths=(500, 2000), ticks=20)
    assert res["idle_scan_flat"]
    for d in res["depths"]:
        assert d["idle_groups_scanned"] == 0
        assert d["burst_batches"] == d["depth"] // res["per_group"]


@pytest.mark.slow
def test_mixed_traffic_drill_smoke():
    """The BENCH_EXEC mixed-traffic harness runs end to end at toy
    scale: both arms certified (v2 partial-order with zero in-chain
    inversions, v1 total-order), minnows jump the whale backlog, and
    reorders actually happened.  The committed artifact's magnitudes
    are the full-scale run's claim, not this smoke's."""
    from benchmarks.exec_bench import run_mixed_traffic_drill

    res = run_mixed_traffic_drill(n_whale=16, n_minnow=4,
                                  whale_ms=6.0, minnow_ms=0.5,
                                  repeats=1)
    assert res["v2_certified_partial_order"]
    assert res["v1_certified_total_order"]
    assert res["v1"]["dispatch_log"]["order_ok"]
    assert res["v2"]["dispatch_log"]["order_ok"]
    assert res["v2"]["overlap_fraction"] > 0
    assert res["v1"]["out_of_order"] == 0
    assert res["minnow_p99_improved"]
