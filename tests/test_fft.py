"""Distributed FFT tests: exactness vs numpy.fft on gathered data (the
golden-comparison strategy of SURVEY §4), round trips, r2c, permuted
layouts, jit fusion."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import PencilArray, PencilFFTPlan, Topology, gather


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def test_c2c_3d_matches_numpy(topo):
    shape = (12, 10, 14)
    rng = np.random.default_rng(0)
    u = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
        np.complex128)
    plan = PencilFFTPlan(topo, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    assert xh.pencil == plan.output_pencil
    np.testing.assert_allclose(gather(xh), np.fft.fftn(u), rtol=1e-10,
                               atol=1e-9)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_r2c_3d_matches_numpy(topo):
    shape = (16, 12, 10)
    u = np.random.default_rng(1).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)
    assert plan.shape_spectral == (9, 12, 10)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    # numpy rfftn transforms the LAST axis r2c; our convention is dim 0
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_ragged_shapes(topo):
    shape = (11, 9, 13)  # nothing divides
    rng = np.random.default_rng(2)
    u = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_extra_dims_batched(topo):
    shape = (8, 12, 10)
    rng = np.random.default_rng(3)
    u = rng.standard_normal(shape + (3,))
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    assert xh.extra_dims == (3,)
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)


def test_no_permute_mode(topo):
    shape = (12, 10, 8)
    u = np.random.default_rng(4).standard_normal(shape).astype(complex)
    plan = PencilFFTPlan(topo, shape, permute=False, dtype=jnp.complex128)
    for pen in plan.pencils:
        assert pen.permutation.is_identity()
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_permuted_layout_places_fft_dim_last(topo):
    plan = PencilFFTPlan(topo, (12, 10, 8), dtype=jnp.complex64)
    for d, pen in enumerate(plan.pencils):
        mem_ids = pen.permutation.apply((0, 1, 2))
        assert mem_ids[-1] == d  # transform dim contiguous in memory


def test_fft_under_jit(topo):
    shape = (12, 10, 8)
    u = np.random.default_rng(5).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)

    @jax.jit
    def roundtrip_energy(x):
        xh = plan.forward(x)
        back = plan.backward(xh)
        return back, jnp.sum(jnp.abs(xh.data) ** 2)

    x = PencilArray.from_global(plan.input_pencil, u)
    back, _ = roundtrip_energy(x)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_slab_1d_topology(devices):
    topo1 = Topology((8,))
    shape = (16, 16, 8)
    u = np.random.default_rng(6).standard_normal(shape).astype(complex)
    plan = PencilFFTPlan(topo1, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_2d_fft(topo, devices):
    # 2D array over 1D topology (M must be < N)
    topo1 = Topology((8,))
    shape = (24, 18)
    u = np.random.default_rng(7).standard_normal(shape).astype(complex)
    plan = PencilFFTPlan(topo1, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_dct_3d_matches_scipy(topo):
    """R2R (DCT-II, ortho) distributed transform — PencilFFTs
    Transforms.R2R parity; real dtype end to end."""
    import scipy.fft as sf

    shape = (12, 10, 14)
    u = np.random.default_rng(8).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transform="dct", dtype=jnp.float64)
    assert plan.dtype_spectral == jnp.float64  # stays real
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = sf.dctn(u, norm="ortho")
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-10)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-12)


@pytest.mark.slow  # ~16 s; the DCT variant stays as the default r2r canary
def test_dst_3d_matches_scipy(topo):
    """DST-II via the DCT identity (no native jax dst) — verified against
    scipy.fft.dstn; completes the R2R family."""
    import scipy.fft as sf

    shape = (12, 10, 14)
    u = np.random.default_rng(9).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transform="dst", dtype=jnp.float64)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = sf.dstn(u, type=2, norm="ortho")
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-10)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-12)


def test_dct_validation(topo):
    with pytest.raises(ValueError, match="transform"):
        PencilFFTPlan(topo, (8, 8, 8), transform="hartley")
    for r2r in ("dct", "dst"):
        with pytest.raises(ValueError, match="implicit"):
            PencilFFTPlan(topo, (8, 8, 8), transform=r2r, real=True)
        with pytest.raises(ValueError, match="real dtype"):
            PencilFFTPlan(topo, (8, 8, 8), transform=r2r,
                          dtype=jnp.complex64)


def test_validation(topo):
    with pytest.raises(ValueError, match="must be <"):
        PencilFFTPlan(topo, (8, 8))  # M == N
    plan = PencilFFTPlan(topo, (8, 8, 8), dtype=jnp.complex64)
    wrong = PencilArray.zeros(plan.output_pencil, dtype=jnp.complex64)
    with pytest.raises(ValueError, match="input_pencil"):
        plan.forward(wrong)


# -- per-dimension transforms (PencilFFTs Transforms-tuple parity) --------

def test_per_dim_rfft_fft_none(topo):
    """transforms=("rfft","fft","none"): each dim carries its own kind
    (PencilFFTs RFFT x FFT x NoTransform, README.md:29-31)."""
    shape = (16, 12, 10)
    u = np.random.default_rng(10).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transforms=("rfft", "fft", "none"),
                         dtype=jnp.float64)
    assert plan.shape_spectral == (9, 12, 10)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = np.fft.fft(np.fft.rfft(u, axis=0), axis=1)
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_per_dim_none_fft_none(topo):
    shape = (8, 12, 10)
    rng = np.random.default_rng(11)
    u = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transforms=("none", "fft", "none"),
                         dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    np.testing.assert_allclose(gather(xh), np.fft.fft(u, axis=1),
                               rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(gather(plan.backward(xh)), u,
                               rtol=1e-10, atol=1e-10)


def test_per_dim_r2r_fourier_mix(topo):
    """DCT on dim 0 (real), then complex FFTs: the R2R x FFT mix."""
    import scipy.fft as sf

    shape = (12, 10, 14)
    u = np.random.default_rng(12).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transforms=("dct", "fft", "fft"),
                         dtype=jnp.float64)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = np.fft.fftn(sf.dct(u, axis=0, norm="ortho"), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_per_dim_all_none_identity(topo):
    shape = (8, 12, 16)
    u = np.random.default_rng(13).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transforms=("none",) * 3,
                         dtype=jnp.float64)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    assert xh.pencil == plan.input_pencil  # no stages -> no movement
    np.testing.assert_array_equal(gather(xh), u)


def test_per_dim_validation(topo):
    with pytest.raises(ValueError, match="entries"):
        PencilFFTPlan(topo, (8, 8, 8), transforms=("fft", "fft"))
    with pytest.raises(ValueError, match="unknown transform kind"):
        PencilFFTPlan(topo, (8, 8, 8), transforms=("fft", "hartley", "fft"))
    with pytest.raises(ValueError, match="at most one"):
        PencilFFTPlan(topo, (8, 8, 8), transforms=("rfft", "rfft", "fft"))
    # real-input kinds must precede fft dims in stage order
    with pytest.raises(ValueError, match="must come first"):
        PencilFFTPlan(topo, (8, 8, 8), transforms=("fft", "rfft", "fft"))
    with pytest.raises(ValueError, match="must come first"):
        PencilFFTPlan(topo, (8, 8, 8), transforms=("fft", "dct", "fft"))
    with pytest.raises(ValueError, match="real dtype"):
        PencilFFTPlan(topo, (8, 8, 8), transforms=("rfft", "fft", "fft"),
                      dtype=jnp.complex64)
    with pytest.raises(ValueError, match="implicit"):
        PencilFFTPlan(topo, (8, 8, 8), transforms=("rfft", "fft", "fft"),
                      real=True)


def test_per_dim_frequencies(topo):
    plan = PencilFFTPlan(topo, (16, 12, 10),
                         transforms=("rfft", "fft", "none"),
                         dtype=jnp.float64)
    np.testing.assert_allclose(plan.frequencies(0), np.fft.rfftfreq(16))
    np.testing.assert_allclose(plan.frequencies(1), np.fft.fftfreq(12))
    with pytest.raises(ValueError, match="none"):
        plan.frequencies(2)


# -- local-dim batching (stage fusion) ------------------------------------

def test_slab_topology_batches_to_one_exchange(devices):
    """On a 1-D (slab) topology two dims are local at stage 0, so a 3-D
    FFT is ONE exchange, not two — the schedule batches local dims into
    a single XLA FFT op (TPU-first divergence from the reference's
    strictly per-dim staging)."""
    import re

    topo1 = Topology((8,))
    shape = (16, 16, 8)
    plan = PencilFFTPlan(topo1, shape, real=True, dtype=jnp.float32)
    x = plan.allocate_input()

    def f(d):
        return plan.forward(PencilArray(plan.input_pencil, d)).data

    hlo = jax.jit(f).lower(x.data).compile().as_text()
    n_a2a = len(re.findall(r" all-to-all\(", hlo))
    assert n_a2a == 1, n_a2a

    # numerics unchanged by batching
    u = np.random.default_rng(14).standard_normal(shape)
    xh = plan.forward(PencilArray.from_global(plan.input_pencil,
                                              u.astype(np.float32)))
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=2e-4, atol=2e-3)


def test_single_device_plan_has_no_collectives():
    """A 1-device plan compiles to one fused native FFT: zero transposes,
    zero collectives — raw-jnp.fft parity by construction."""
    import re

    topo1 = Topology((1,), devices=jax.devices()[:1])
    plan = PencilFFTPlan(topo1, (16, 12, 10), real=True, dtype=jnp.float32)
    assert len(plan._steps) == 1  # single batched stage
    x = plan.allocate_input()

    def f(d):
        return plan.forward(PencilArray(plan.input_pencil, d)).data

    hlo = jax.jit(f).lower(x.data).compile().as_text()
    for op in ("all-to-all", "all-gather", "collective-permute"):
        assert not re.findall(rf" {op}\(", hlo), op
    u = np.random.default_rng(15).standard_normal((16, 12, 10)).astype(
        np.float32)
    xh = plan.forward(PencilArray.from_global(plan.input_pencil, u))
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=2e-4, atol=2e-3)


def test_per_dim_validation_topology_independent(devices):
    """The stage-order rule is enforced on the conceptual chain, not the
    batched schedule: the same transforms tuple raises identically on a
    slab mesh (which could batch the dims) and a 2-D mesh."""
    for topo_i in (Topology((8,)), Topology((2, 4))):
        with pytest.raises(ValueError, match="must come first"):
            PencilFFTPlan(topo_i, (8, 8, 8, 8),
                          transforms=("fft", "rfft", "fft", "fft"))


def test_4d_per_dim_transforms(topo):
    """4-D array over the 2-D mesh with mixed per-dim kinds — the N=4,
    M=2 configuration of BASELINE config 4, on the FFT layer."""
    shape = (8, 12, 10, 6)
    u = np.random.default_rng(16).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape,
                         transforms=("rfft", "fft", "none", "fft"),
                         dtype=jnp.float64)
    assert plan.shape_spectral == (5, 12, 10, 6)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 3))
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_extent_aware_chain_avoids_stranding(devices):
    """Round-3 fix (dryrun weak #1): the stage chain is chosen by extent,
    so the post-rfft shrunken dim rides the SMALL mesh axis and no stage
    strands a device.  Pinned chain for the flagship dryrun config."""
    topo = Topology((4, 2))
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)  # empty-rank warning fails
        plan = PencilFFTPlan(topo, (16, 12, 20), real=True,
                             dtype=jnp.float64)
    # dim 0 shrinks 16 -> 9: it must never sit on the size-4 axis
    # (slot 0); dim 2 (size 20) takes that axis instead.
    assert [p.decomposition for p in plan.pencils] == \
        [(2, 1), (2, 0), (1, 0)]
    u = np.random.default_rng(21).standard_normal((16, 12, 20))
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(gather(plan.backward(xh)), u,
                               rtol=1e-10, atol=1e-10)


def test_extent_aware_chain_symmetric_keeps_legacy(devices):
    """Cost ties resolve to the classic x->y->z chain: symmetric plans
    are bit-stable across the round-3 chain search."""
    plan = PencilFFTPlan(Topology((2, 4)), (16, 16, 16),
                         dtype=jnp.complex64)
    assert [p.decomposition for p in plan.pencils] == \
        [(1, 2), (0, 2), (0, 1)]


def test_none_dim_relaxes_chain_hops(devices):
    """A dim with transform='none' never needs to be local, so the chain
    search may leave it decomposed and skip a hop: 4-D fft/none/fft/fft
    over a 2-D mesh runs in 1 exchange instead of 3."""
    topo = Topology((4, 2))
    plan = PencilFFTPlan(topo, (16, 16, 16, 16),
                         transforms=("fft", "none", "fft", "fft"),
                         dtype=jnp.complex128)
    hops = sum(1 for s in plan._steps if s[0] == "t")
    assert hops == 1
    u = np.random.default_rng(22).standard_normal((16, 16, 16, 16)) \
        .astype(complex)
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)),
                               np.fft.fftn(u, axes=(0, 2, 3)),
                               rtol=1e-9, atol=1e-8)


def test_forward_backward_donate(topo):
    """donate=True round-trips identically (eager per-hop donation —
    the in-place ManyPencilArray analog, multiarrays.jl:106-130); under
    jit the flag is inert by design (XLA owns buffer reuse there)."""
    shape = (16, 12, 20)
    u = np.random.default_rng(23).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)
    x_keep = PencilArray.from_global(plan.input_pencil, u)
    ref = gather(plan.forward(x_keep))

    x2 = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x2, donate=True)  # x2 now invalid (on TPU)
    np.testing.assert_allclose(gather(xh), ref, rtol=1e-12, atol=1e-12)
    back = plan.backward(xh, donate=True)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)

    # traced path: no inner-jit donation warnings, identical numbers
    x3 = PencilArray.from_global(plan.input_pencil, u)

    @jax.jit
    def rt(d):
        a = PencilArray(plan.input_pencil, d)
        return plan.backward(plan.forward(a, donate=True),
                             donate=True).data
    np.testing.assert_allclose(gather(PencilArray(plan.input_pencil,
                                                  rt(x3.data))),
                               u, rtol=1e-10, atol=1e-10)


def test_ring_method_plan_end_to_end(topo):
    """A full plan with method=Ring(): values identical to AllToAll and
    to numpy (the methods are bit-identical per hop; this pins it
    through a whole multi-stage r2c plan, ragged shapes included)."""
    from pencilarrays_tpu import Ring

    shape = (11, 9, 13)
    u = np.random.default_rng(24).standard_normal(shape)
    plan_r = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64,
                           method=Ring())
    plan_a = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)
    x = PencilArray.from_global(plan_r.input_pencil, u)
    xh_r = plan_r.forward(x)
    xh_a = plan_a.forward(PencilArray.from_global(plan_a.input_pencil, u))
    np.testing.assert_array_equal(gather(xh_r), gather(xh_a))  # bit-equal
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh_r), expect, rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(gather(plan_r.backward(xh_r)), u,
                               rtol=1e-10, atol=1e-10)


def test_elided_hop_rfft_keeps_memory_order(devices):
    """Regression (found by the fuzz sweep): with a 'none' leading dim on
    a 1-D mesh the stage-1 hop is elided, so the rfft executes in stage
    0's memory order — the post-shrinkage pencil must keep THAT
    permutation, not the chain slot's (the bug produced a transposed
    block shape and a construction-time ValueError)."""
    from pencilarrays_tpu import Topology

    topo1 = Topology((8,))
    shape = (8, 7, 13)
    kinds = ("none", "rfft", "fft")
    plan = PencilFFTPlan(topo1, shape, transforms=kinds, dtype=jnp.float64)
    u = np.random.default_rng(77).standard_normal(shape)
    x = PencilArray.from_global(plan.input_pencil, u)
    uh = plan.forward(x)
    expect = np.fft.fft(np.fft.rfft(u, axis=1), axis=2)
    np.testing.assert_allclose(gather(uh), expect, rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(gather(plan.backward(uh)), u,
                               rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward", "none"])
def test_normalization_modes(topo, norm):
    """PencilFFTs normalization taxonomy: values match numpy's norm= for
    the Fourier dims; round trip is identity scaled by scale_factor()
    (1 except for 'none', the unnormalized-BFFT convention)."""
    shape = (12, 10, 8)
    u = np.random.default_rng(31).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64,
                         normalization=norm)
    x = PencilArray.from_global(plan.input_pencil, u)
    uh = plan.forward(x)
    np_norm = None if norm in ("backward", "none") else norm
    expect = np.fft.fftn(np.fft.rfft(u, axis=0, norm=np_norm),
                         axes=(1, 2), norm=np_norm)
    np.testing.assert_allclose(gather(uh), expect, rtol=1e-9, atol=1e-9)
    back = plan.backward(uh)
    s = plan.scale_factor()
    assert s == (float(np.prod(shape)) if norm == "none" else 1.0)
    np.testing.assert_allclose(gather(back), s * u, rtol=1e-9, atol=1e-7)


def test_normalization_ortho_parseval(topo):
    """ortho mode preserves the L2 norm through an all-fft plan."""
    shape = (8, 12, 10)
    rng = np.random.default_rng(32)
    u = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, dtype=jnp.complex128,
                         normalization="ortho")
    x = PencilArray.from_global(plan.input_pencil, u)
    uh = plan.forward(x)
    np.testing.assert_allclose(
        float(jnp.sum(jnp.abs(gather(uh)) ** 2)),
        float(np.sum(np.abs(u) ** 2)), rtol=1e-10)


def test_normalization_validated(topo):
    with pytest.raises(ValueError, match="normalization"):
        PencilFFTPlan(topo, (8, 8, 8), normalization="weird")


# -- pipelined (fused chunked-exchange) hops -------------------------------


def test_pipeline_k1_reproduces_serialized_schedule(topo):
    """pipeline=1 (and None) keep the exact serialized step tuple —
    the degenerate case is REALLY the current path, not a lookalike."""
    p0 = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float64)
    p1 = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float64,
                       pipeline=1)
    assert p1._steps == p0._steps
    assert all(s[0] in ("t", "f") for s in p1._steps)


@pytest.mark.parametrize("pipeline", [2, 4])
def test_pipeline_forward_backward_equivalence(topo, pipeline):
    """Fused pipelined hops change scheduling, not values: forward and
    backward match the serialized plan and numpy on an r2c plan."""
    shape = (16, 12, 10)
    u = np.random.default_rng(41).standard_normal(shape)
    p0 = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)
    pk = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64,
                       pipeline=pipeline)
    assert any(s[0] == "ft" for s in pk._steps)
    x = PencilArray.from_global(pk.input_pencil, u)
    uh = pk.forward(x)
    uh0 = p0.forward(PencilArray.from_global(p0.input_pencil, u))
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(uh), expect, rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(gather(uh), gather(uh0),
                               rtol=1e-12, atol=1e-12)
    back = pk.backward(uh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)
    # eager per-hop donation flows through the fused steps too
    x2 = PencilArray.from_global(pk.input_pencil, u)
    uh2 = pk.forward(x2, donate=True)
    np.testing.assert_allclose(gather(pk.backward(uh2, donate=True)), u,
                               rtol=1e-10, atol=1e-10)


def test_pipeline_ragged_shapes(topo):
    """Ragged extents: chunk bounds, tail padding and the fused unpack
    all stay exact."""
    shape = (11, 9, 13)
    rng = np.random.default_rng(42)
    u = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    pk = PencilFFTPlan(topo, shape, dtype=jnp.complex128, pipeline=3)
    x = PencilArray.from_global(pk.input_pencil, u)
    np.testing.assert_allclose(gather(pk.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(gather(pk.backward(pk.forward(x))), u,
                               rtol=1e-10, atol=1e-10)


def test_pipeline_under_jit_and_grad(topo):
    """The fused hop is traceable and differentiable end to end (the
    chunked exchange and per-chunk transforms all have transpose
    rules)."""
    shape = (16, 12, 10)
    u = np.random.default_rng(43).standard_normal(shape)
    pk = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64,
                       pipeline=2)
    p0 = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)

    def loss(plan, d):
        uh = plan.forward(PencilArray(plan.input_pencil, d))
        return jnp.sum(jnp.abs(uh.data) ** 2)

    x = PencilArray.from_global(pk.input_pencil, u)
    g = jax.jit(jax.grad(lambda d: loss(pk, d)))(x.data)
    g0 = jax.grad(lambda d: loss(p0, d))(x.data)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g0),
                               rtol=1e-10, atol=1e-10)


def test_pipeline_collective_costs_match_hlo(topo):
    """The byte model stays predictive through fusion: chunking
    multiplies collective COUNT, never bytes — pinned equal to the
    compiled HLO's measured stats."""
    from pencilarrays_tpu.utils.hlo import collective_stats

    pk = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float64,
                       pipeline=4)
    p0 = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float64)
    x = pk.allocate_input()
    hlo = jax.jit(
        lambda d: pk.forward(PencilArray(pk.input_pencil, d)).data
    ).lower(x.data).compile().as_text()
    predicted = pk.collective_costs()
    assert predicted == collective_stats(hlo)
    # same wire bytes as the serialized plan, more launches
    serial = p0.collective_costs()
    assert predicted["all-to-all"]["bytes"] == \
        serial["all-to-all"]["bytes"]
    assert predicted["all-to-all"]["count"] > \
        serial["all-to-all"]["count"]


def test_pipeline_auto_and_validation(topo, monkeypatch):
    """pipeline='auto' follows the measured sweep verdict when one
    exists (mtime-invalidated artifact loader), else the literature
    default; bad values raise."""
    import pencilarrays_tpu.ops.fft as fft_mod

    with pytest.raises(ValueError, match="pipeline"):
        PencilFFTPlan(topo, (8, 8, 8), pipeline=0)
    with pytest.raises(ValueError, match="pipeline"):
        PencilFFTPlan(topo, (8, 8, 8), pipeline="fast")

    monkeypatch.setattr(fft_mod, "_pipeline_sweep_verdict",
                        lambda p=None: {"best_k": 2, "pipelined_wins": True})
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float64, pipeline="auto")
    assert plan.pipeline_chunks == 2
    monkeypatch.setattr(fft_mod, "_pipeline_sweep_verdict",
                        lambda p=None: None)
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float64, pipeline="auto")
    assert plan.pipeline_chunks == fft_mod._PIPELINE_AUTO_DEFAULT_K
    # a verdict that measured serialized winning keeps the plan serial
    monkeypatch.setattr(fft_mod, "_pipeline_sweep_verdict",
                        lambda p=None: {"best_k": 1, "pipelined_wins": False})
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float64, pipeline="auto")
    assert all(s[0] in ("t", "f") for s in plan._steps)


def test_pipeline_sweep_verdict_platform_gated(tmp_path, monkeypatch):
    """An artifact captured on a DIFFERENT backend must not route
    pipeline='auto' (a CPU virtual-mesh sweep measures chunking
    overhead, not overlap): the loader returns None unless the recorded
    platform matches the current one."""
    import json

    import pencilarrays_tpu.ops.fft as fft_mod

    art = tmp_path / "PIPELINE_SWEEP.json"
    monkeypatch.setenv("PENCILARRAYS_TPU_PIPELINE_SWEEP_PATH", str(art))
    art.write_text(json.dumps({"platform": jax.default_backend(),
                               "verdict": {"best_k": 2}}))
    assert fft_mod._pipeline_sweep_verdict() == {"best_k": 2}
    art.write_text(json.dumps({"platform": "not-this-backend",
                               "verdict": {"best_k": 8}}))
    import os

    os.utime(art, ns=(1, 1))
    assert fft_mod._pipeline_sweep_verdict() is None
    # legacy artifact with no platform field: accepted as-is
    art.write_text(json.dumps({"verdict": {"best_k": 4}}))
    os.utime(art, ns=(2, 2))
    assert fft_mod._pipeline_sweep_verdict() == {"best_k": 4}


def test_pipeline_single_device_plan_unchanged():
    """One device: no hops exist, pipeline=K is inert and the plan still
    compiles to the single fused FFT."""
    topo1 = Topology((1,), devices=jax.devices()[:1])
    plan = PencilFFTPlan(topo1, (16, 12, 10), real=True,
                         dtype=jnp.float32, pipeline=4)
    assert len(plan._steps) == 1 and plan._steps[0][0] == "f"
