"""Distributed FFT tests: exactness vs numpy.fft on gathered data (the
golden-comparison strategy of SURVEY §4), round trips, r2c, permuted
layouts, jit fusion."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import PencilArray, PencilFFTPlan, Topology, gather


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def test_c2c_3d_matches_numpy(topo):
    shape = (12, 10, 14)
    rng = np.random.default_rng(0)
    u = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
        np.complex128)
    plan = PencilFFTPlan(topo, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    assert xh.pencil == plan.output_pencil
    np.testing.assert_allclose(gather(xh), np.fft.fftn(u), rtol=1e-10,
                               atol=1e-9)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_r2c_3d_matches_numpy(topo):
    shape = (16, 12, 10)
    u = np.random.default_rng(1).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)
    assert plan.shape_spectral == (9, 12, 10)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    # numpy rfftn transforms the LAST axis r2c; our convention is dim 0
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_ragged_shapes(topo):
    shape = (11, 9, 13)  # nothing divides
    rng = np.random.default_rng(2)
    u = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_extra_dims_batched(topo):
    shape = (8, 12, 10)
    rng = np.random.default_rng(3)
    u = rng.standard_normal(shape + (3,))
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    assert xh.extra_dims == (3,)
    expect = np.fft.fftn(np.fft.rfft(u, axis=0), axes=(1, 2))
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-8)


def test_no_permute_mode(topo):
    shape = (12, 10, 8)
    u = np.random.default_rng(4).standard_normal(shape).astype(complex)
    plan = PencilFFTPlan(topo, shape, permute=False, dtype=jnp.complex128)
    for pen in plan.pencils:
        assert pen.permutation.is_identity()
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_permuted_layout_places_fft_dim_last(topo):
    plan = PencilFFTPlan(topo, (12, 10, 8), dtype=jnp.complex64)
    for d, pen in enumerate(plan.pencils):
        mem_ids = pen.permutation.apply((0, 1, 2))
        assert mem_ids[-1] == d  # transform dim contiguous in memory


def test_fft_under_jit(topo):
    shape = (12, 10, 8)
    u = np.random.default_rng(5).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, real=True, dtype=jnp.float64)

    @jax.jit
    def roundtrip_energy(x):
        xh = plan.forward(x)
        back = plan.backward(xh)
        return back, jnp.sum(jnp.abs(xh.data) ** 2)

    x = PencilArray.from_global(plan.input_pencil, u)
    back, _ = roundtrip_energy(x)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)


def test_slab_1d_topology(devices):
    topo1 = Topology((8,))
    shape = (16, 16, 8)
    u = np.random.default_rng(6).standard_normal(shape).astype(complex)
    plan = PencilFFTPlan(topo1, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_2d_fft(topo, devices):
    # 2D array over 1D topology (M must be < N)
    topo1 = Topology((8,))
    shape = (24, 18)
    u = np.random.default_rng(7).standard_normal(shape).astype(complex)
    plan = PencilFFTPlan(topo1, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    np.testing.assert_allclose(gather(plan.forward(x)), np.fft.fftn(u),
                               rtol=1e-9, atol=1e-8)


def test_dct_3d_matches_scipy(topo):
    """R2R (DCT-II, ortho) distributed transform — PencilFFTs
    Transforms.R2R parity; real dtype end to end."""
    import scipy.fft as sf

    shape = (12, 10, 14)
    u = np.random.default_rng(8).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transform="dct", dtype=jnp.float64)
    assert plan.dtype_spectral == jnp.float64  # stays real
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = sf.dctn(u, norm="ortho")
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-10)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-12)


def test_dst_3d_matches_scipy(topo):
    """DST-II via the DCT identity (no native jax dst) — verified against
    scipy.fft.dstn; completes the R2R family."""
    import scipy.fft as sf

    shape = (12, 10, 14)
    u = np.random.default_rng(9).standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, transform="dst", dtype=jnp.float64)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    expect = sf.dstn(u, type=2, norm="ortho")
    np.testing.assert_allclose(gather(xh), expect, rtol=1e-9, atol=1e-10)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-12)


def test_dct_validation(topo):
    with pytest.raises(ValueError, match="transform"):
        PencilFFTPlan(topo, (8, 8, 8), transform="hartley")
    for r2r in ("dct", "dst"):
        with pytest.raises(ValueError, match="implicit"):
            PencilFFTPlan(topo, (8, 8, 8), transform=r2r, real=True)
        with pytest.raises(ValueError, match="real dtype"):
            PencilFFTPlan(topo, (8, 8, 8), transform=r2r,
                          dtype=jnp.complex64)


def test_validation(topo):
    with pytest.raises(ValueError, match="must be <"):
        PencilFFTPlan(topo, (8, 8))  # M == N
    plan = PencilFFTPlan(topo, (8, 8, 8), dtype=jnp.complex64)
    wrong = PencilArray.zeros(plan.output_pencil, dtype=jnp.complex64)
    with pytest.raises(ValueError, match="input_pencil"):
        plan.forward(wrong)
