"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh).

The kernel must match :func:`dense_attention` bitwise-close under true
f32 matmuls, across unaligned lengths (block padding + key-tail
masking), causal wedges, cross-length offsets, and bf16 inputs; its
``custom_vjp`` backward must match the XLA scan path's gradient.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu.models.attention import (
    _flash_xla,
    dense_attention,
    flash_attention,
)
from pencilarrays_tpu.ops.flash_pallas import pallas_flash_attention, supported


def _qkv(rng, sq, skv, h, b, d, dtype=jnp.float32):
    shape_q = (sq, h, b, d) if b else (sq, h, d)
    shape_k = (skv, h, b, d) if b else (skv, h, d)
    q = jnp.asarray(rng.standard_normal(shape_q), dtype)
    k = jnp.asarray(rng.standard_normal(shape_k), dtype)
    v = jnp.asarray(rng.standard_normal(shape_k), dtype)
    return q, k, v


def test_supported_predicate():
    f32 = jnp.float32
    assert supported(256, 256, 64, f32, q_offset=0, kv_offset=0)
    assert supported(256, 256, 64, jnp.bfloat16, q_offset=0, kv_offset=0)
    # traced offsets are fine for the kernel itself (they ride in SMEM);
    # only the public flash_attention routing restricts them to static
    # ints (its custom_vjp hashes them as nondiff args) — see below
    assert supported(256, 256, 64, f32,
                     q_offset=jnp.int32(0), kv_offset=0)
    assert not supported(256, 256, 64, jnp.float64,
                         q_offset=0, kv_offset=0)
    assert not supported(256, 256, 60, f32, q_offset=0, kv_offset=0)
    # tiny shapes: XLA path on real accelerators, accepted on CPU tests
    assert not supported(64, 64, 64, f32, q_offset=0, kv_offset=0,
                         platform="tpu")
    assert supported(64, 64, 64, f32, q_offset=0, kv_offset=0,
                     platform="cpu")


@pytest.mark.parametrize("sq,skv,h,b,d", [
    (128, 128, 2, 0, 32),     # aligned, no batch dim
    (80, 80, 3, 2, 16),       # unaligned rows + key tail padding
    (300, 140, 1, 1, 64),     # cross-length, multiple k blocks w/ pad
    (16, 520, 2, 0, 8),       # skv > block, ragged tail
])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense(sq, skv, h, b, d, causal):
    rng = np.random.default_rng(7)
    q, k, v = _qkv(rng, sq, skv, h, b, d)
    with jax.default_matmul_precision("float32"):
        ref = dense_attention(q, k, v, causal=causal)
        got = pallas_flash_attention(q, k, v, causal=causal,
                                     interpret=True, block_q=64,
                                     block_k=128)
    # start-aligned convention: every row sees key 0, so no rows are
    # unspecified here (offsets are exercised in test_offsets_match_dense)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=5e-6, rtol=5e-6)


@pytest.mark.parametrize("q_off,kv_off", [(5, 0), (0, 3), (17, 9)])
def test_offsets_match_dense(q_off, kv_off):
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, 72, 96, 2, 1, 16)
    with jax.default_matmul_precision("float32"):
        ref = dense_attention(q, k, v, causal=True,
                              q_offset=q_off, kv_offset=kv_off)
        got = pallas_flash_attention(q, k, v, causal=True,
                                     q_offset=q_off, kv_offset=kv_off,
                                     interpret=True, block_q=32,
                                     block_k=128)
    # rows whose visible-key set is empty are unspecified in both
    rows_ok = (q_off + np.arange(72)) >= kv_off
    np.testing.assert_allclose(np.asarray(got)[rows_ok],
                               np.asarray(ref)[rows_ok],
                               atol=5e-6, rtol=5e-6)


def test_bf16():
    rng = np.random.default_rng(11)
    q, k, v = _qkv(rng, 128, 128, 2, 1, 32, jnp.bfloat16)
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32))
    got = pallas_flash_attention(q, k, v, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


def test_fully_masked_rows_finite():
    """q rows before the kv origin see no keys; output must stay finite
    (the dense reference's unspecified-but-finite contract)."""
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, 16, 16, 1, 0, 8)
    got = pallas_flash_attention(q, k, v, causal=True, kv_offset=8,
                                 interpret=True)
    assert bool(jnp.isfinite(got).all())


def test_flash_attention_impl_routing():
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, 64, 64, 2, 1, 16)
    with jax.default_matmul_precision("float32"):
        ref = flash_attention(q, k, v, impl="xla")
        got = flash_attention(q, k, v, impl="pallas")  # interpret on CPU
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=5e-6, rtol=5e-6)
    with pytest.raises(ValueError):
        flash_attention(q, k, v.astype(jnp.float64), impl="pallas")
    with pytest.raises(ValueError):
        flash_attention(q, k, v, impl="nope")
    # traced offsets: the public routing guard, not supported(), rejects
    with pytest.raises(ValueError):
        flash_attention(q, k, v, impl="pallas", q_offset=jnp.int32(0))


@pytest.mark.parametrize("causal", [False, True])
def test_custom_vjp_matches_xla_grad(causal):
    rng = np.random.default_rng(9)
    q, k, v = _qkv(rng, 48, 48, 2, 1, 16)
    ct = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

    def loss_pallas(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=causal,
                                       impl="pallas") * ct)

    def loss_xla(q_, k_, v_):
        return jnp.sum(_flash_xla(q_, k_, v_, causal=causal, chunk=None,
                                  q_offset=0, kv_offset=0) * ct)

    with jax.default_matmul_precision("float32"):
        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("sq,skv,q_off,kv_off", [
    (80, 140, 0, 0),      # unaligned rows + ragged key tail
    pytest.param(72, 96, 5, 3,       # cross-length with offsets
                 marks=pytest.mark.slow),
    pytest.param(16, 520, 0, 9,      # many key blocks, offset origin
                 marks=pytest.mark.slow),
])
def test_pallas_bwd_kernels_match_xla_grad(sq, skv, q_off, kv_off):
    """The hand-tiled dq/dk/dv backward kernels (exercised through the
    public custom_vjp route) must match the XLA scan path's gradient on
    unaligned, cross-length, offset-causal cases — the same coverage
    grid as the forward."""
    rng = np.random.default_rng(31)
    q, k, v = _qkv(rng, sq, skv, 2, 1, 16)
    ct = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    # rows with an empty visible-key set have unspecified OUTPUT (each
    # impl returns different finite garbage), so a nonzero cotangent
    # there would propagate impl-specific gradients into dk/dv — zero
    # it, exactly as a real loss over defined outputs would
    rows_ok = (q_off + np.arange(sq)) >= kv_off
    ct = ct * jnp.asarray(rows_ok, jnp.float32)[:, None, None, None]

    def loss(impl):
        def f(q_, k_, v_):
            return jnp.sum(flash_attention(
                q_, k_, v_, causal=True, impl=impl,
                q_offset=q_off, kv_offset=kv_off) * ct)
        return f

    with jax.default_matmul_precision("float32"):
        gp = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_pallas_bwd_bf16_grad_close_to_f32():
    rng = np.random.default_rng(37)
    q, k, v = _qkv(rng, 64, 64, 2, 1, 32, jnp.bfloat16)
    ct = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

    def loss_p(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, impl="pallas")
                       .astype(jnp.float32) * ct)

    def loss_f32(q_, k_, v_):
        return jnp.sum(_flash_xla(q_, k_, v_, causal=False, chunk=None,
                                  q_offset=0, kv_offset=0) * ct)

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    with jax.default_matmul_precision("float32"):
        gx = jax.grad(loss_f32, argnums=(0, 1, 2))(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32))
    for a, b in zip(gp, gx):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b), atol=6e-2, rtol=6e-2)


def test_return_stats_matches_partials():
    """return_stats must hand back the same (m, l) the partials mode
    computes (folded layout), alongside the normalized output."""
    rng = np.random.default_rng(41)
    S, H, B, D = 64, 2, 1, 16
    q = jnp.asarray(rng.standard_normal((S, H, B, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((S, H, B, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((S, H, B, D)), jnp.float32)
    with jax.default_matmul_precision("float32"):
        out, (m, l) = pallas_flash_attention(q, k, v, interpret=True,
                                             return_stats=True)
        mp, lp, _ = pallas_flash_attention(q, k, v, partials=True,
                                           interpret=True)
        plain = pallas_flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(plain),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(m), np.asarray(mp).reshape(
        H * B, S), atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(l), np.asarray(lp).reshape(
        H * B, S), atol=1e-6, rtol=1e-6)


@pytest.mark.slow  # ~30 s: interpret-mode kernel + grad on the mesh
def test_ulysses_pallas_impl_on_mesh(devices):
    """The Ulysses wiring for the Pallas local kernel: the outer
    ``_use_pallas_flash`` probe must agree with the inner decision (so
    ``check_vma`` is set consistently), and the forward + grad through
    the ``custom_vjp`` must match the XLA impl on the virtual mesh."""
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import dense_attention, ulysses_attention

    P = 4
    topo = pa.Topology((P,), devices=devices[:P])
    S, H, D = 32, 8, 16
    pen = pa.Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(21)

    def mk():
        return pa.PencilArray.from_global(
            pen, rng.standard_normal((S, H, D)).astype(np.float32),
            extra_ndims=1)

    q, k, v = mk(), mk(), mk()
    with jax.default_matmul_precision("float32"):
        ref = dense_attention(np.asarray(pa.gather(q)),
                              np.asarray(pa.gather(k)),
                              np.asarray(pa.gather(v)))
        out = ulysses_attention(q, k, v, impl="pallas")
        np.testing.assert_allclose(np.asarray(pa.gather(out)),
                                   np.asarray(ref), atol=1e-5, rtol=1e-5)

        def loss(data, impl):
            u = pa.PencilArray(pen, data, (D,))
            o = ulysses_attention(u, k, v, impl=impl)
            return jnp.sum(o.data ** 2)

        gp = jax.grad(lambda d: loss(d, "pallas"))(q.data)
        gx = jax.grad(lambda d: loss(d, "xla"))(q.data)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                                   atol=1e-5, rtol=1e-5)


def test_ulysses_pallas_mixed_dtypes(devices):
    """The check_vma probe must mirror the inner decision: stack()
    promotes mixed q/k/v dtypes to one result dtype, so bf16 k/v with
    f32 q still routes through the Pallas kernel without tripping the
    static varying-mesh-axes check."""
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import dense_attention, ulysses_attention

    P = 2
    topo = pa.Topology((P,), devices=devices[:P])
    S, H, D = 16, 4, 8
    pen = pa.Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(13)

    def mk(dtype):
        u = pa.PencilArray.from_global(
            pen, rng.standard_normal((S, H, D)).astype(np.float32),
            extra_ndims=1)
        return pa.PencilArray(pen, u.data.astype(dtype), (D,))

    q, k, v = mk(jnp.float32), mk(jnp.bfloat16), mk(jnp.bfloat16)
    out = ulysses_attention(q, k, v, impl="pallas")
    ref = dense_attention(np.asarray(pa.gather(q), np.float32),
                          np.asarray(pa.gather(k), np.float32),
                          np.asarray(pa.gather(v), np.float32))
    np.testing.assert_allclose(np.asarray(pa.gather(out)),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


@pytest.mark.slow  # ~2 min each: interpret-mode kernel x ring rounds x grad
@pytest.mark.parametrize("causal", [False, True])
def test_ring_pallas_impl_on_mesh(devices, causal):
    """Ring attention with the kernel in partials mode: one Pallas call
    per round with the round's traced offsets, merged exactly — must
    match dense and the XLA ring, and stay differentiable."""
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import dense_attention, ring_attention

    P = 4
    topo = pa.Topology((P,), devices=devices[:P])
    S, H, D = 32, 2, 16
    pen = pa.Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(17)

    def mk():
        return pa.PencilArray.from_global(
            pen, rng.standard_normal((S, H, D)).astype(np.float32),
            extra_ndims=1)

    q, k, v = mk(), mk(), mk()
    with jax.default_matmul_precision("float32"):
        ref = dense_attention(np.asarray(pa.gather(q)),
                              np.asarray(pa.gather(k)),
                              np.asarray(pa.gather(v)), causal=causal)
        out_p = ring_attention(q, k, v, causal=causal, impl="pallas")
        out_x = ring_attention(q, k, v, causal=causal, impl="xla")
    np.testing.assert_allclose(np.asarray(pa.gather(out_p)),
                               np.asarray(ref), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pa.gather(out_p)),
                               np.asarray(pa.gather(out_x)),
                               atol=1e-5, rtol=1e-5)

    def loss(data, impl):
        u = pa.PencilArray(pen, data, (D,))
        o = ring_attention(u, k, v, causal=causal, impl=impl)
        return jnp.sum(o.data ** 2)

    with jax.default_matmul_precision("float32"):
        gp = jax.grad(lambda d: loss(d, "pallas"))(q.data)
        gx = jax.grad(lambda d: loss(d, "xla"))(q.data)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                               atol=1e-5, rtol=1e-5)


def test_ring_zigzag_pallas_force_rejects_tiny_head_dim(devices):
    """impl='pallas' forcing still surfaces supported()'s verdict (d=4
    does not tile the lane axis)."""
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import ring_attention, to_zigzag

    P = 2
    topo = pa.Topology((P,), devices=devices[:P])
    pen = pa.Pencil(topo, (16, 2), (0,))
    u = pa.PencilArray.zeros(pen, (4,))
    z = to_zigzag(u)
    with pytest.raises(ValueError):
        ring_attention(z, z, z, causal=True, zigzag=True, impl="pallas")


@pytest.mark.slow  # interpret-mode kernels x zigzag pairs x grad
@pytest.mark.parametrize("P", [2, 3, 4])  # incl. odd P: the past/future
# split is asymmetric there (verified ad hoc round 5, pinned here)
def test_zigzag_pallas_impl_on_mesh(devices, P):
    """The kernelized zigzag schedule (VERDICT r4 #3/#4): every pair one
    partials kernel call under the pair's traced offsets, hand-tiled
    ring backward — must match dense attention and the XLA zigzag path
    in BOTH directions."""
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import (
        dense_attention, from_zigzag, ring_attention, to_zigzag)

    topo = pa.Topology((P,), devices=devices[:P])
    S, H, D = 16 * P, 2, 16
    pen = pa.Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(29)

    def mk():
        return pa.PencilArray.from_global(
            pen, rng.standard_normal((S, H, D)).astype(np.float32),
            extra_ndims=1)

    q, k, v = mk(), mk(), mk()
    qz, kz, vz = map(to_zigzag, (q, k, v))
    with jax.default_matmul_precision("float32"):
        ref = dense_attention(np.asarray(pa.gather(q)),
                              np.asarray(pa.gather(k)),
                              np.asarray(pa.gather(v)), causal=True)
        out_p = from_zigzag(ring_attention(qz, kz, vz, causal=True,
                                           zigzag=True, impl="pallas"))
        out_x = from_zigzag(ring_attention(qz, kz, vz, causal=True,
                                           zigzag=True, impl="xla"))
    np.testing.assert_allclose(np.asarray(pa.gather(out_p)),
                               np.asarray(ref), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pa.gather(out_p)),
                               np.asarray(pa.gather(out_x)),
                               atol=1e-5, rtol=1e-5)

    def loss(dq, dk, dv, impl):
        o = ring_attention(pa.PencilArray(pen, dq, (D,)),
                           pa.PencilArray(pen, dk, (D,)),
                           pa.PencilArray(pen, dv, (D,)),
                           causal=True, zigzag=True, impl=impl)
        return jnp.sum(o.data ** 2)

    with jax.default_matmul_precision("float32"):
        gp = jax.grad(loss, argnums=(0, 1, 2))(qz.data, kz.data, vz.data,
                                               "pallas")
        gx = jax.grad(loss, argnums=(0, 1, 2))(qz.data, kz.data, vz.data,
                                               "xla")
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # interpret-mode kernels x ring rounds x full grad
@pytest.mark.parametrize("causal", [False, True])
def test_ring_pallas_bwd_kernels_full_grad(devices, causal):
    """The hand-tiled ring backward (global-logsumexp recompute with the
    rotating dk/dv accumulator) must match the XLA ring's gradient for
    ALL of q, k, v — not just q."""
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import ring_attention

    P = 4
    topo = pa.Topology((P,), devices=devices[:P])
    S, H, D = 32, 2, 16
    pen = pa.Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(43)

    def mk():
        return pa.PencilArray.from_global(
            pen, rng.standard_normal((S, H, D)).astype(np.float32),
            extra_ndims=1)

    q, k, v = mk(), mk(), mk()

    def loss(dq, dk, dv, impl):
        o = ring_attention(pa.PencilArray(pen, dq, (D,)),
                           pa.PencilArray(pen, dk, (D,)),
                           pa.PencilArray(pen, dv, (D,)),
                           causal=causal, impl=impl)
        return jnp.sum(o.data ** 2)

    with jax.default_matmul_precision("float32"):
        gp = jax.grad(loss, argnums=(0, 1, 2))(q.data, k.data, v.data,
                                               "pallas")
        gx = jax.grad(loss, argnums=(0, 1, 2))(q.data, k.data, v.data,
                                               "xla")
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_partials_merge_matches_full():
    """Kernel partials over two disjoint key halves, merged, must equal
    the full-key kernel output."""
    from pencilarrays_tpu.models.attention import (
        _flash_finish, _merge_partials)
    from pencilarrays_tpu.ops.flash_pallas import pallas_flash_attention

    rng = np.random.default_rng(23)
    S, H, B, D = 64, 2, 1, 16
    q = jnp.asarray(rng.standard_normal((S, H, B, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((S, H, B, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((S, H, B, D)), jnp.float32)
    with jax.default_matmul_precision("float32"):
        full = pallas_flash_attention(q, k, v, interpret=True)
        p1 = pallas_flash_attention(q, k[:32], v[:32], partials=True,
                                    interpret=True)
        p2 = pallas_flash_attention(q, k[32:], v[32:], partials=True,
                                    interpret=True)
        merged = _flash_finish(*_merge_partials(p1, p2), jnp.float32)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(full),
                               atol=1e-6, rtol=1e-6)


def test_jit_and_shapes_preserved():
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, 40, 40, 2, 3, 8)
    f = jax.jit(lambda q_, k_, v_: flash_attention(q_, k_, v_,
                                                   impl="pallas"))
    out = f(q, k, v)
    assert out.shape == q.shape and out.dtype == q.dtype


def test_auto_routing_consults_measured_verdict(monkeypatch):
    """impl='auto' must be gated by the real-chip sweep verdict when one
    exists (the permute-kernel measured-verdict discipline): a measured
    loss turns the default off; no measurement keeps the tiling-argument
    default; the env knob always wins."""
    from pencilarrays_tpu.models import attention as attn

    monkeypatch.delenv("PENCILARRAYS_TPU_PALLAS_ATTENTION", raising=False)
    monkeypatch.setattr(attn, "_flash_sweep_verdict",
                        lambda: {"fwd_all_win": False})
    assert not attn._auto_pallas_allowed()
    monkeypatch.setattr(attn, "_flash_sweep_verdict",
                        lambda: {"fwd_all_win": True})
    assert attn._auto_pallas_allowed()
    monkeypatch.setattr(attn, "_flash_sweep_verdict", lambda: None)
    assert attn._auto_pallas_allowed()
    monkeypatch.setenv("PENCILARRAYS_TPU_PALLAS_ATTENTION", "0")
    assert not attn._auto_pallas_allowed()


def test_bwd_routing_consults_fwd_bwd_verdict(monkeypatch):
    """With the env knob UNSET, a measured fwd+bwd LOSS routes the flash
    backward through the XLA recompute while the (separately measured)
    Pallas forward stays — the ADVICE r5 medium finding.  An explicit
    env setting always wins, in either direction."""
    from pencilarrays_tpu.models import attention as attn

    monkeypatch.delenv("PENCILARRAYS_TPU_FLASH_BWD", raising=False)
    monkeypatch.setattr(attn, "_flash_sweep_verdict",
                        lambda: {"fwd_all_win": True,
                                 "fwd_bwd_all_win": False})
    assert not attn._hand_bwd_enabled()
    monkeypatch.setattr(attn, "_flash_sweep_verdict",
                        lambda: {"fwd_all_win": True,
                                 "fwd_bwd_all_win": True})
    assert attn._hand_bwd_enabled()
    monkeypatch.setattr(attn, "_flash_sweep_verdict", lambda: None)
    assert attn._hand_bwd_enabled()  # no measurement: tiling default
    # explicit env overrides the measured verdict both ways
    monkeypatch.setattr(attn, "_flash_sweep_verdict",
                        lambda: {"fwd_bwd_all_win": False})
    monkeypatch.setenv("PENCILARRAYS_TPU_FLASH_BWD", "pallas")
    assert attn._hand_bwd_enabled()
    monkeypatch.setenv("PENCILARRAYS_TPU_FLASH_BWD", "xla")
    assert not attn._hand_bwd_enabled()


def test_flash_sweep_artifact_env_override_and_mtime(tmp_path,
                                                     monkeypatch):
    """PENCILARRAYS_TPU_FLASH_SWEEP_PATH points the verdict loader
    anywhere (installed layouts), and a rewritten artifact is re-read on
    mtime change — no process-lifetime lru pin (ADVICE r5 low #2)."""
    import json
    import os

    from pencilarrays_tpu.models import attention as attn

    art = tmp_path / "sweep.json"
    art.write_text(json.dumps({"verdict": {"fwd_all_win": True}}))
    monkeypatch.setenv("PENCILARRAYS_TPU_FLASH_SWEEP_PATH", str(art))
    assert attn._flash_sweep_verdict() == {"fwd_all_win": True}
    # rewrite + distinct mtime -> the loader must pick up the new doc
    art.write_text(json.dumps({"verdict": {"fwd_all_win": False}}))
    os.utime(art, ns=(1, 1))
    assert attn._flash_sweep_verdict() == {"fwd_all_win": False}
    # missing file: None (and the stale cache entry is dropped)
    art.unlink()
    assert attn._flash_sweep_verdict() is None


@pytest.mark.slow  # interpret-mode kernels x ring rounds, bf16
def test_ring_pallas_bf16_on_mesh(devices):
    """bf16 q/k/v through the kernelized ring: f32 statistics inside the
    kernels, bf16 on the wire and in the gradients."""
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import dense_attention, ring_attention

    P = 2
    topo = pa.Topology((P,), devices=devices[:P])
    S, H, D = 16, 2, 16
    pen = pa.Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(51)

    def mk():
        u = pa.PencilArray.from_global(
            pen, rng.standard_normal((S, H, D)).astype(np.float32),
            extra_ndims=1)
        return pa.PencilArray(pen, u.data.astype(jnp.bfloat16), (D,))

    q, k, v = mk(), mk(), mk()
    ref = dense_attention(np.asarray(pa.gather(q), np.float32),
                          np.asarray(pa.gather(k), np.float32),
                          np.asarray(pa.gather(v), np.float32),
                          causal=True)
    out = ring_attention(q, k, v, causal=True, impl="pallas")
    assert out.data.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(pa.gather(out), np.float32), np.asarray(ref),
        atol=4e-2, rtol=4e-2)

    def loss(d):
        o = ring_attention(pa.PencilArray(pen, d, (D,)), k, v,
                           causal=True, impl="pallas")
        return jnp.sum(o.data.astype(jnp.float32) ** 2)

    g = jax.grad(loss)(q.data)
    assert g.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


def test_flash_bwd_escape_hatch(monkeypatch):
    """PENCILARRAYS_TPU_FLASH_BWD=xla must keep the Pallas forward but
    produce the XLA-recompute gradient (identical to the full XLA
    path) — the one-flag fallback if the hand backward misbehaves on
    some chip."""
    rng = np.random.default_rng(61)
    q, k, v = _qkv(rng, 48, 48, 2, 1, 16)
    ct = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

    def loss(impl):
        def f(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, causal=True,
                                           impl=impl) * ct)
        return f

    monkeypatch.setenv("PENCILARRAYS_TPU_FLASH_BWD", "xla")
    with jax.default_matmul_precision("float32"):
        gp = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # interpret-mode ring rounds x grad, twice
def test_ring_bwd_escape_hatch_on_mesh(devices, monkeypatch):
    import pencilarrays_tpu as pa
    from pencilarrays_tpu.models import ring_attention

    P = 2
    topo = pa.Topology((P,), devices=devices[:P])
    S, H, D = 16, 2, 16
    pen = pa.Pencil(topo, (S, H), (0,))
    rng = np.random.default_rng(67)

    def mk():
        return pa.PencilArray.from_global(
            pen, rng.standard_normal((S, H, D)).astype(np.float32),
            extra_ndims=1)

    q, k, v = mk(), mk(), mk()

    def loss(d, impl):
        o = ring_attention(pa.PencilArray(pen, d, (D,)), k, v,
                           causal=True, impl=impl)
        return jnp.sum(o.data ** 2)

    monkeypatch.setenv("PENCILARRAYS_TPU_FLASH_BWD", "xla")
    with jax.default_matmul_precision("float32"):
        gp = jax.grad(lambda d: loss(d, "pallas"))(q.data)
        gx = jax.grad(lambda d: loss(d, "xla"))(q.data)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                               atol=1e-5, rtol=1e-5)
