"""Multi-mesh fleet federation (ISSUE 17): the two-tier placement
cost model, the KV wire codec, mesh health leases with one-round-lag
beat GC, whole-mesh failover with the exactly-once resolution
contract, the ``%mesh`` fault selector, the joiner-spawning
supervisor, and the ``fleet-event`` lint rule.

Boundary contracts under test (the satellite checklist):

* a week of heartbeats holds <= 2 live beat keys per mesh (the
  one-round-lag GC regression count);
* lease expiry is typed ``MeshFailureError`` (with ``age_s``), clean
  departure typed ``MeshLeftError`` — never conflated;
* double failover (A dies -> rebind B -> B dies -> rebind C) resolves
  the ticket EXACTLY once, on C, with the correct result;
* a mesh that published its result and THEN died resolves from the
  result — zero rebinds, never a duplicate;
* typed serve errors cross the wire as the SAME class; kwargs that
  fail to reconstruct degrade to ``FleetError``, never raise inside
  the decoder;
* every ``fleet.*`` journal literal is registered and emitted only
  from ``fleet/`` (the ``fleet-event`` rule).
"""

import os
import textwrap
import time

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.analysis.lint import lint_tree
from pencilarrays_tpu.cluster.kv import FileKV
from pencilarrays_tpu.fleet import (
    MESH_ENV,
    FleetCost,
    FleetRouter,
    FleetSupervisor,
    MeshBoard,
    MeshFailureError,
    MeshLease,
    MeshLeftError,
    MeshWorker,
    mesh_id,
)
from pencilarrays_tpu.fleet import wire
from pencilarrays_tpu.fleet.errors import FleetError
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.resilience import faults
from pencilarrays_tpu.resilience.errors import InjectedFault
from pencilarrays_tpu.serve import (
    SLO,
    AdmissionError,
    DeadlineError,
    PlanService,
)

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (obs.ENV_VAR, faults.ENV_VAR, MESH_ENV,
                "PENCILARRAYS_TPU_FLEET_SPAWN",
                "PENCILARRAYS_TPU_FLEET_DCN_LATENCY_BYTES",
                "PENCILARRAYS_TPU_FLEET_DCN_FACTOR",
                "PENCILARRAYS_TPU_FLEET_COMPILE_PENALTY"):
        monkeypatch.delenv(var, raising=False)
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _kv(tmp_path, sub="kv"):
    return FileKV(os.path.join(str(tmp_path), sub))


def _service(devices, shape=(8, 6, 4), name="fft"):
    topo = pa.Topology((1,), devices=devices[:1])
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    svc.register_plan(name, lambda ctx: PencilFFTPlan(topo, shape))
    return svc


def _worker(kv, mesh, devices, *, ttl=0.3, warm=True, **kw):
    w = MeshWorker(kv, mesh, service=_service(devices), ttl=ttl, **kw)
    if warm:
        w.prewarm(["fft"])
    return w


def _host(seed, shape=(8, 6, 4)):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


# ---------------------------------------------------------------------------
# the two-tier cost model
# ---------------------------------------------------------------------------


def test_cost_model_units():
    c = FleetCost()
    # colo: the router's own failure domain pays no DCN toll
    assert c.wire_bytes(nbytes_in=1000, nbytes_out=1000,
                        tier="colo") == 0.0
    # dcn: 2x latency toll + per-byte factor, both directions
    assert c.wire_bytes(nbytes_in=1000, nbytes_out=500, tier="dcn") \
        == 2 * c.dcn_latency_bytes + c.dcn_byte_factor * 1500
    assert c.affinity_bytes(warm=True) == 0.0
    assert c.affinity_bytes(warm=False) == float(c.compile_penalty_bytes)
    # SLO tenants weight queue depth harder
    assert c.backlog_bytes(backlog=100.0, deadline_s=None) == 100.0
    assert c.backlog_bytes(backlog=100.0, deadline_s=1.0) \
        == c.slo_drain_weight * 100.0
    s = c.score(nbytes_in=10, nbytes_out=10, tier="dcn", warm=False,
                backlog=5.0)
    assert s["total"] == s["wire"] + s["affinity"] + s["backlog"]


def test_cost_from_env(monkeypatch):
    monkeypatch.setenv("PENCILARRAYS_TPU_FLEET_DCN_LATENCY_BYTES", "100")
    monkeypatch.setenv("PENCILARRAYS_TPU_FLEET_DCN_FACTOR", "2.5")
    monkeypatch.setenv("PENCILARRAYS_TPU_FLEET_COMPILE_PENALTY", "77")
    c = FleetCost.from_env()
    assert c.dcn_latency_bytes == 100
    assert c.dcn_byte_factor == 2.5
    assert c.compile_penalty_bytes == 77
    # garbage falls back to defaults, never raises
    monkeypatch.setenv("PENCILARRAYS_TPU_FLEET_DCN_FACTOR", "fast")
    assert FleetCost.from_env().dcn_byte_factor \
        == FleetCost().dcn_byte_factor


# ---------------------------------------------------------------------------
# the KV wire: key layout + codec
# ---------------------------------------------------------------------------


def test_wire_key_layout():
    # zero-padded sequence numbers: lexical order IS numeric order,
    # so MeshBoard's max() over a listing finds the newest beat
    k9 = wire.beat_key("pa", 1, 9)
    k10 = wire.beat_key("pa", 1, 10)
    assert k9 < k10
    assert k9.startswith("pa/fleet/beat/m1/")
    assert wire.ticket_id_of(wire.req_key("pa", 2, "abc")) == "abc"
    assert wire.ticket_id_of(wire.res_key("pa", "abc")) == "abc"
    assert wire.req_key("pa", 2, "abc").startswith(
        wire.req_dir("pa", 2) + "/")


def test_wire_request_roundtrip():
    payload = _host(0)
    raw = wire.encode_request(
        "t1", tenant="acme", name="fft", direction="forward",
        payload=payload, t_submit=123.0, deadline_s=1.5, rebinds=2)
    req = wire.decode_request(raw)
    assert req["tenant"] == "acme" and req["name"] == "fft"
    assert req["deadline_s"] == 1.5 and req["rebinds"] == 2
    assert req["payload"].dtype == payload.dtype
    np.testing.assert_array_equal(req["payload"], payload)


def test_wire_result_roundtrips():
    value = _host(1)
    meta, got, err = wire.decode_result(
        wire.encode_result("t1", value=value, seconds=0.5, mesh=3))
    assert err is None and meta["mesh"] == 3
    np.testing.assert_array_equal(got, value)

    # typed serve errors re-raise as the SAME class with their kwargs
    e = AdmissionError("no", tenant="acme", reason="shed")
    _, _, got_e = wire.decode_result(wire.encode_result("t2", error=e))
    assert isinstance(got_e, AdmissionError)
    assert got_e.tenant == "acme" and got_e.reason == "shed"

    e2 = DeadlineError("late", tenant="acme", reason="projected",
                       deadline_s=2.0, projected_s=3.5)
    _, _, got_e2 = wire.decode_result(wire.encode_result("t3", error=e2))
    assert isinstance(got_e2, DeadlineError)
    assert got_e2.deadline_s == 2.0 and got_e2.projected_s == 3.5

    # an unknown type degrades to FleetError carrying the name —
    # never arbitrary reconstruction, never a silent swallow
    _, _, got_e3 = wire.decode_result(wire.encode_result(
        "t4", error=ValueError("boom")))
    assert isinstance(got_e3, FleetError)
    assert "ValueError" in str(got_e3)

    # kwargs that fail to reconstruct degrade too (a registry class
    # whose required kwargs were stripped must not raise in the decoder)
    import json as _json

    raw = _json.loads(wire.encode_result(
        "t5", error=AdmissionError("no", tenant="a", reason="shed")))
    raw["error"]["kwargs"] = {}
    _, _, got_e4 = wire.decode_result(_json.dumps(raw))
    assert isinstance(got_e4, FleetError)

    with pytest.raises(ValueError):
        wire.encode_result("t6")            # neither value nor error
    with pytest.raises(ValueError):
        wire.encode_result("t7", value=value, error=e)


# ---------------------------------------------------------------------------
# the %mesh fault selector
# ---------------------------------------------------------------------------


def test_mesh_selector_parse():
    r, = faults.parse("fleet.route:kill%mesh1@4")
    assert r.point == "fleet.route" and r.mode == "kill"
    assert r.mesh == 1 and r.rank is None and r.first == 4
    r2, = faults.parse("hop.exchange:error%rank2")
    assert r2.rank == 2 and r2.mesh is None


def test_mesh_selector_addresses_one_mesh(monkeypatch):
    assert mesh_id() == -1          # not a mesh worker by default
    with faults.active("fleet.route:error%mesh1"):
        faults.fire("fleet.route")  # mesh -1: not addressed
        monkeypatch.setenv(MESH_ENV, "2")
        faults.fire("fleet.route")  # mesh 2: not addressed
        monkeypatch.setenv(MESH_ENV, "1")
        assert mesh_id() == 1
        with pytest.raises(InjectedFault):
            faults.fire("fleet.route")
    # an unaddressed rule fires for every process
    with faults.active("fleet.route:error"):
        with pytest.raises(InjectedFault):
            faults.fire("fleet.route")


# ---------------------------------------------------------------------------
# health leases: beat GC, expiry, clean departure
# ---------------------------------------------------------------------------


def test_beat_gc_bounded(tmp_path):
    """The one-round-lag GC regression count: many renewals, <= 2 live
    beat keys — the KV store cannot grow with uptime."""
    kv = _kv(tmp_path)
    lease = MeshLease(kv, 0, ttl=5.0)
    for _ in range(50):
        lease.renew()
    assert lease.renewals == 50
    live = kv.list_dir(wire.beat_dir("pa", 0))
    assert 1 <= len(live) <= 2
    board = MeshBoard(kv, ttl=5.0)
    age = board.mesh_age(0)
    assert age is not None and age < 1.0


def test_lease_expiry_is_typed_mesh_failure(tmp_path):
    kv = _kv(tmp_path)
    MeshLease(kv, 0, ttl=0.2).renew()       # one beat, then silence
    board = MeshBoard(kv, ttl=0.2, join_grace=0.2)
    assert board.live_meshes([0]) == [0]
    time.sleep(0.35)
    dead = board.dead_meshes([0])
    assert len(dead) == 1
    mesh, err = dead[0]
    assert mesh == 0 and isinstance(err, MeshFailureError)
    assert err.mesh == 0 and err.age_s is not None and err.age_s > 0.2
    with pytest.raises(MeshFailureError):
        board.check([0])
    assert board.live_meshes([0]) == []


def test_clean_departure_is_typed_mesh_left(tmp_path):
    kv = _kv(tmp_path)
    lease = MeshLease(kv, 3, ttl=0.2)
    lease.renew()
    lease.leave()
    board = MeshBoard(kv, ttl=0.2, join_grace=0.2)
    assert board.live_meshes([3]) == []     # left: never a candidate
    time.sleep(0.3)
    (mesh, err), = board.dead_meshes([3])
    assert mesh == 3 and isinstance(err, MeshLeftError)
    assert not isinstance(err, MeshFailureError)


def test_never_seen_mesh_respects_join_grace(tmp_path):
    kv = _kv(tmp_path)
    board = MeshBoard(kv, ttl=0.2, join_grace=10.0)
    assert board.live_meshes([7]) == []     # not alive until 1st beat
    assert board.dead_meshes([7]) == []     # but not dead either: grace
    board2 = MeshBoard(kv, ttl=0.2, join_grace=0.05)
    time.sleep(0.1)
    (_, err), = board2.dead_meshes([7])
    assert isinstance(err, MeshFailureError) and err.age_s is None


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------


def _fake_mesh(kv, mesh, *, queued=0, warm=True, fp="fp-1"):
    """A mesh that exists only as wire state: one beat + one load
    export — placement inputs without a real worker."""
    MeshLease(kv, mesh, ttl=5.0).renew()
    import json

    kv.set(wire.load_key("pa", mesh), json.dumps({
        "t": time.time(), "mesh": mesh, "tier": "dcn",
        "projection": {"queued_cost_bytes": queued,
                       "inflight_cost_bytes": 0},
        "plans": {"fft": fp}, "warm": [fp] if warm else [],
    }))


def test_placement_prefers_warm_fingerprint(tmp_path):
    kv = _kv(tmp_path)
    _fake_mesh(kv, 1, warm=False)
    _fake_mesh(kv, 2, warm=True)
    router = FleetRouter(kv, ttl=5.0)
    router.register_mesh(1)
    router.register_mesh(2)
    mesh, score = router._place("fft", 1024, None)
    assert mesh == 2 and score["affinity"] == 0.0


def test_placement_prefers_shallow_backlog_and_colo(tmp_path):
    kv = _kv(tmp_path)
    _fake_mesh(kv, 1, queued=512 * 1024 * 1024)
    _fake_mesh(kv, 2, queued=0)
    router = FleetRouter(kv, ttl=5.0)
    router.register_mesh(1)
    router.register_mesh(2)
    mesh, _ = router._place("fft", 1024, None)
    assert mesh == 2
    # identical load: the colo tier's zero DCN toll wins
    kv2 = _kv(tmp_path, "kv2")
    _fake_mesh(kv2, 1)
    _fake_mesh(kv2, 2)
    router2 = FleetRouter(kv2, ttl=5.0)
    router2.register_mesh(1, tier="colo")
    router2.register_mesh(2)
    mesh2, score2 = router2._place("fft", 1024, None)
    assert mesh2 == 1 and score2["wire"] == 0.0


def test_no_live_mesh_is_typed_admission_error(tmp_path):
    router = FleetRouter(_kv(tmp_path), ttl=0.2)
    router.register_mesh(1)                 # registered but never beat
    with pytest.raises(AdmissionError) as ei:
        router.submit("acme", np.zeros((4, 4), np.complex64),
                      name="fft")
    assert ei.value.reason == "no-mesh" and ei.value.tenant == "acme"
    assert router.stats()["submitted"] == 0


def test_discover_registers_exporting_meshes(tmp_path):
    kv = _kv(tmp_path)
    _fake_mesh(kv, 4)
    _fake_mesh(kv, 9)
    router = FleetRouter(kv, ttl=5.0)
    assert sorted(router.discover()) == [4, 9]
    assert router.meshes() == [4, 9]
    assert router.discover() == []          # idempotent


# ---------------------------------------------------------------------------
# end-to-end over the wire (in-process workers, stepped manually)
# ---------------------------------------------------------------------------


def test_single_mesh_end_to_end(tmp_path, devices):
    obs.enable(str(tmp_path / "obs"))
    kv = _kv(tmp_path)
    worker = _worker(kv, 1, devices)
    worker.start()
    router = FleetRouter(kv, ttl=0.3)
    router.register_mesh(1)
    try:
        u = _host(2)
        t = router.submit("acme", u, name="fft")
        assert worker.step() == 1
        router.pump()
        got = np.asarray(t.result(5.0))
        np.testing.assert_allclose(got, np.fft.fftn(u), rtol=1e-4,
                                   atol=1e-4)
        stats = router.stats()
        assert stats["completed"] == 1 and stats["pending"] == 0
        # the wire is empty after resolution (req + res both GC'd)
        assert kv.list_dir(wire.req_dir("pa", 1)) == {}
        assert kv.try_get(wire.res_key("pa", t.id)) is None
    finally:
        worker.close()
        router.close()
        obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    evs = [e["ev"] for e in events]
    assert "fleet.lease" in evs
    routes = [e for e in events if e["ev"] == "fleet.route"]
    assert [r["reason"] for r in routes] == ["placed"]
    assert routes[0]["mesh"] == 1 and routes[0]["tenant"] == "acme"
    assert obs.lint_journal(events) == []


def test_typed_error_crosses_the_wire(tmp_path, devices):
    """A worker-side failure resolves the router-side ticket with the
    SAME typed error — here an InjectedFault from the mesh's own
    ``fleet.route`` admission point (hit 2: the router's submit-side
    fire is hit 1)."""
    kv = _kv(tmp_path)
    worker = _worker(kv, 1, devices)
    worker.start()
    router = FleetRouter(kv, ttl=0.3)
    router.register_mesh(1)
    try:
        with faults.active("fleet.route:error@2"):
            t = router.submit("acme", _host(3), name="fft")
            worker.step()
        router.pump()
        with pytest.raises(InjectedFault):
            t.result(5.0)
        assert t.error().point == "fleet.route"
        assert router.stats()["failed"] == 1
    finally:
        worker.close()
        router.close()


def test_router_deadline_safety_net(tmp_path, devices):
    """A ticket whose mesh is alive but never executes fails typed at
    its SLO deadline — the router's own enforcement point for budgets
    that lapse before any service sees the request."""
    kv = _kv(tmp_path)
    worker = _worker(kv, 1, devices)
    worker.start()                          # heartbeats, never steps
    router = FleetRouter(kv, ttl=5.0,
                         slos={"acme": SLO(deadline_s=0.05)})
    router.register_mesh(1)
    try:
        t = router.submit("acme", _host(4), name="fft")
        time.sleep(0.1)
        router.pump()
        err = t.error()
        assert isinstance(err, DeadlineError)
        assert err.reason == "expired" and err.deadline_s == 0.05
        assert router.stats()["expired"] == 1
    finally:
        worker.close()
        router.close()


# ---------------------------------------------------------------------------
# failover
# ---------------------------------------------------------------------------


def test_failover_rebinds_to_sibling(tmp_path, devices):
    obs.enable(str(tmp_path / "obs"))
    kv = _kv(tmp_path)
    w1 = _worker(kv, 1, devices)
    w2 = _worker(kv, 2, devices, warm=False)
    w1.start()
    w2.start()
    router = FleetRouter(kv, ttl=0.3)
    router.register_mesh(1)
    router.register_mesh(2)
    try:
        u = _host(5)
        t = router.submit("acme", u, name="fft")    # warm: mesh 1
        assert kv.list_dir(wire.req_dir("pa", 1)) != {}
        w1.stop()                           # whole-mesh death
        time.sleep(0.5)
        router.pump()                       # detect + park + rebind
        assert w2.step() == 1
        router.pump()
        np.testing.assert_allclose(np.asarray(t.result(5.0)),
                                   np.fft.fftn(u), rtol=1e-4, atol=1e-4)
        stats = router.stats()
        assert stats["rebound"] == 1 and stats["completed"] == 1
        assert stats["dead_meshes"] == [1]
    finally:
        w1.close()
        w2.close()
        router.close()
        obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    fo = [e for e in events if e["ev"] == "fleet.failover"]
    assert len(fo) == 1 and fo[0]["mesh"] == 1 and fo[0]["tickets"] == 1
    assert fo[0]["detect_s"] > 0.3          # ~ttl, never instant
    reasons = [e["reason"] for e in events if e["ev"] == "fleet.route"]
    assert reasons == ["placed", "rebind"]
    assert obs.lint_journal(events) == []


def test_double_failover_resolves_exactly_once(tmp_path, devices):
    """The satellite drill: A dies -> rebind to B -> B dies -> rebind
    to C -> resolves exactly once, correct, on C."""
    kv = _kv(tmp_path)
    workers = {m: _worker(kv, m, devices, warm=(m == 1))
               for m in (1, 2, 3)}
    for w in workers.values():
        w.start()
    router = FleetRouter(kv, ttl=0.3)
    for m in workers:
        router.register_mesh(m)
    try:
        u = _host(6)
        t = router.submit("acme", u, name="fft")    # warm: mesh 1
        workers[1].stop()
        time.sleep(0.5)
        router.pump()                       # rebind 1 (cold tie -> 2)
        assert kv.list_dir(wire.req_dir("pa", 2)) != {}
        workers[2].stop()                   # the sibling dies too
        time.sleep(0.5)
        router.pump()                       # rebind 2 -> mesh 3
        assert workers[3].step() == 1
        router.pump()
        np.testing.assert_allclose(np.asarray(t.result(5.0)),
                                   np.fft.fftn(u), rtol=1e-4, atol=1e-4)
        stats = router.stats()
        assert stats["completed"] == 1 and stats["failed"] == 0
        assert stats["rebound"] == 2 and stats["duplicates"] == 0
        assert stats["dead_meshes"] == [1, 2]
        assert stats["pending"] == 0
    finally:
        for w in workers.values():
            w.close()
        router.close()


def test_published_result_survives_mesh_death(tmp_path, devices):
    """A mesh that completed the work and THEN died resolves from its
    published result — zero rebinds, zero wasted re-execution."""
    kv = _kv(tmp_path)
    w1 = _worker(kv, 1, devices)
    w2 = _worker(kv, 2, devices)
    w1.start()
    w2.start()
    router = FleetRouter(kv, ttl=0.3)
    router.register_mesh(1)
    router.register_mesh(2)
    try:
        u = _host(7)
        t = router.submit("acme", u, name="fft")
        assert w1.step() == 1               # result published...
        w1.stop()                           # ...then the mesh dies
        time.sleep(0.5)
        router.pump()
        np.testing.assert_allclose(np.asarray(t.result(5.0)),
                                   np.fft.fftn(u), rtol=1e-4, atol=1e-4)
        stats = router.stats()
        assert stats["completed"] == 1 and stats["rebound"] == 0
    finally:
        w1.close()
        w2.close()
        router.close()


def test_all_meshes_dead_fails_typed(tmp_path, devices):
    """Whole-fleet loss: the pending ticket ends in a typed
    ``AdmissionError(reason="no-mesh")`` — exactly one outcome, never
    a hang."""
    kv = _kv(tmp_path)
    w1 = _worker(kv, 1, devices)
    w1.start()
    router = FleetRouter(kv, ttl=0.3)
    router.register_mesh(1)
    try:
        t = router.submit("acme", _host(8), name="fft")
        w1.stop()
        time.sleep(0.5)
        router.pump()
        err = t.error()
        assert isinstance(err, AdmissionError)
        assert err.reason == "no-mesh"
        assert router.stats()["pending"] == 0
    finally:
        w1.close()
        router.close()


def test_retire_via_stop_key_is_clean_departure(tmp_path, devices):
    kv = _kv(tmp_path)
    sup = FleetSupervisor(spawn=lambda m: None, kv=kv)
    w = _worker(kv, 5, devices)
    w.start()
    sup.retire(5)
    assert w.step() == 0
    assert w.stopped
    assert kv.try_get(wire.left_key("pa", 5)) is not None
    board = MeshBoard(kv, ttl=5.0)
    assert board.mesh_left(5)
    w.close()


# ---------------------------------------------------------------------------
# the fleet supervisor (demand-signal consumer)
# ---------------------------------------------------------------------------


def _demand(reason="overload"):
    return {"direction": "up", "acted": False, "detail": "no-joiner",
            "reason": reason}


def test_supervisor_is_flag_gated():
    spawned = []
    sup = FleetSupervisor(spawn=spawned.append, cooldown_s=0.0)
    assert not sup.enabled                  # env flag off by default
    assert not sup.observe(_demand())
    assert spawned == []


def test_supervisor_spawns_with_cooldown_and_cap():
    spawned = []
    sup = FleetSupervisor(spawn=spawned.append, enabled=True,
                          cooldown_s=30.0, max_meshes=2, next_mesh=1)
    assert sup.observe(_demand())
    assert spawned == [1]
    assert not sup.observe(_demand())       # cooldown
    sup2 = FleetSupervisor(spawn=spawned.append, enabled=True,
                           cooldown_s=0.0, max_meshes=2, next_mesh=1)
    assert sup2.observe(_demand()) and sup2.observe(_demand())
    assert not sup2.observe(_demand())      # at-capacity
    assert sup2.spawned == [1, 2]
    # non-demand records are ignored outright
    assert not sup2.observe({"direction": "down", "acted": True})
    assert not sup2.observe({"direction": "up", "acted": True,
                             "detail": "no-joiner"})


def test_supervisor_scan_dedupes_by_journal_identity(tmp_path):
    """Replaying the same journal never double-spawns: consumed
    signals are keyed by ``(proc, seq)``."""
    jdir = str(tmp_path / "obs")
    obs.enable(jdir)
    obs.record_event("serve.scale", action="grow", reason="overload",
                     direction="up", acted=False, detail="no-joiner")
    obs.record_event("serve.scale", action="grow", reason="overload",
                     direction="up", acted=False, detail="no-joiner")
    obs.record_event("serve.scale", action="grow", reason="overload",
                     direction="up", acted=True)      # not a demand
    obs.disable()
    spawned = []
    sup = FleetSupervisor(spawn=spawned.append, enabled=True,
                          cooldown_s=0.0)
    assert sup.scan(jdir) == 2
    assert spawned == [1, 2]
    assert sup.scan(jdir) == 0              # replay: all deduped
    assert spawned == [1, 2]
    assert sup.stats()["signals_seen"] == 3


# ---------------------------------------------------------------------------
# the fleet-event lint rule
# ---------------------------------------------------------------------------


def _write(root, rel, content):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(content))


def _lint_fixture(tmp_path, fleet_src, outside_src=""):
    root = str(tmp_path / "repo")
    _write(root, "pencilarrays_tpu/obs/schema.py", """
        EVENT_TYPES = {"fleet.route": ("ticket",), "hop": ("method",)}
        """)
    _write(root, "pencilarrays_tpu/resilience/faults.py", """
        POINTS = frozenset({"io.open"})
        """)
    _write(root, "docs/Resilience.md", "| `io.open` |")
    _write(root, "README.md", "docs")
    _write(root, "pencilarrays_tpu/fleet/router.py", fleet_src)
    if outside_src:
        _write(root, "pencilarrays_tpu/serve/thing.py", outside_src)
    return root


def test_lint_fleet_event_rules(tmp_path):
    root = _lint_fixture(tmp_path, """
        def f(obs, name):
            obs.record_event("fleet.route", ticket="t")   # fine
            obs.record_event("fleet.bogus", ticket="t")   # unregistered
            obs.record_event(name, ticket="t")            # dynamic
            obs.record_event("hop", method="x")           # not fleet.*
        """, outside_src="""
        def g(obs):
            obs.record_event("fleet.route", ticket="t")   # wrong layer
        """)
    found = sorted((f.ident, f.path.replace(os.sep, "/"))
                   for f in lint_tree(root) if f.check == "fleet-event")
    assert found == [
        ("fleet.bogus", "pencilarrays_tpu/fleet/router.py"),
        ("fleet.route", "pencilarrays_tpu/serve/thing.py"),
        ("fleet.router:dynamic", "pencilarrays_tpu/fleet/router.py"),
        ("hop", "pencilarrays_tpu/fleet/router.py"),
    ]


def test_lint_clean_fleet_fixture(tmp_path):
    root = _lint_fixture(tmp_path, """
        def f(obs):
            obs.record_event("fleet.route", ticket="t")
        """)
    assert [f for f in lint_tree(root) if f.check == "fleet-event"] == []
