"""The ISSUE 17 acceptance drill: whole-mesh chaos across real OS
processes.

Two (three in the slow variant) subprocess ``PlanService`` meshes
(``fleet_worker.py``) join a front-end :class:`FleetRouter` through a
shared ``FileKV`` directory; a mixed whale/minnow storm is submitted;
one whole mesh is SIGKILLed mid-storm by the fleet-addressed fault
spec ``fleet.route:kill%mesh1@4`` (the SAME spec in every worker's
environment — the ``%mesh`` selector does the addressing).  The
router must detect the loss by lease expiry (typed
``MeshFailureError``, ``detect_s`` well under 20 s), re-bind the dead
mesh's tickets to the sibling, and resolve EVERY submitted ticket
exactly once with the bit-correct FFT — after which the merged fleet
timeline must render lint-clean through the real ``pa-obs`` CLI.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from pencilarrays_tpu import obs
from pencilarrays_tpu.cluster.kv import FileKV
from pencilarrays_tpu.fleet import FleetRouter, MeshBoard
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.resilience import faults

TTL = 2.0
BOOT_S = 90.0       # jax import + plan compile on a cold worker
SHAPES = {"minnow": (8, 6, 4), "whale": (16, 12, 8)}


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _spawn(kvroot, mesh, tmpdir, *, fault=""):
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(here),
        "PA_FLEET_TEST_TTL": str(TTL),
        "PENCILARRAYS_TPU_FAULTS": fault,
    })
    env.pop("PENCILARRAYS_TPU_FLEET_MESH", None)
    env.pop("PENCILARRAYS_TPU_CLUSTER_RANK", None)
    return subprocess.Popen(
        [sys.executable, os.path.join(here, "fleet_worker.py"),
         kvroot, str(mesh), tmpdir, "120"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _await_live(kv, meshes):
    board = MeshBoard(kv, ttl=TTL)
    deadline = time.monotonic() + BOOT_S
    while time.monotonic() < deadline:
        if board.live_meshes(meshes) == sorted(meshes):
            return
        time.sleep(0.1)
    raise AssertionError(f"meshes {meshes} never all came alive")


def _reap(procs, timeout=30):
    outs = {}
    for mesh, p in procs.items():
        try:
            outs[mesh], _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            outs[mesh], _ = p.communicate()
    return outs


def _host(seed, shape):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


def test_whole_mesh_loss_mid_storm(tmp_path):
    """The acceptance drill proper: 2 subprocess meshes, mixed storm,
    mesh 1 SIGKILLed by its own 4th routed request."""
    kvroot = str(tmp_path / "kv")
    obsdir = str(tmp_path / "obs")
    kv = FileKV(kvroot)
    procs = {m: _spawn(kvroot, m, str(tmp_path),
                       fault="fleet.route:kill%mesh1@4")
             for m in (1, 2)}
    router = None
    try:
        _await_live(kv, [1, 2])
        obs.enable(obsdir)
        router = FleetRouter(kv, ttl=TTL)
        router.register_mesh(1)
        router.register_mesh(2)

        # wave 1: a mixed burst — placement sends it to one mesh
        # (both warm, zero backlog: the tie breaks low), whose 4th
        # take is the SIGKILL
        tickets = []
        for i in range(12):
            tenant = "whale" if i % 3 == 0 else "minnow"
            u = _host(i, SHAPES[tenant])
            tickets.append((router.submit(tenant, u, name=tenant), u))
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            router.pump()
            if router.stats()["dead_meshes"]:
                break
            time.sleep(0.05)
        assert router.stats()["dead_meshes"] == [1]
        assert procs[1].wait(timeout=30) == -signal.SIGKILL

        # wave 2: the storm continues against the surviving sibling
        for i in range(12, 16):
            tenant = "whale" if i % 3 == 0 else "minnow"
            u = _host(i, SHAPES[tenant])
            tickets.append((router.submit(tenant, u, name=tenant), u))

        assert router.drain(60.0) == 0
        stats = router.stats()
        # every submitted ticket resolved exactly once, bit-correct
        assert stats["completed"] == len(tickets)
        assert stats["failed"] == 0 and stats["duplicates"] == 0
        for t, u in tickets:
            np.testing.assert_allclose(np.asarray(t.result(1.0)),
                                       np.fft.fftn(u),
                                       rtol=1e-3, atol=1e-3)
    finally:
        if router is not None:
            router.close()
        obs.disable()
        for m in (1, 2):
            kv.set(f"pa/fleet/stop/m{m}", "stop")
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
        outs = _reap(procs)

    # mesh 2 survived the whole drill and executed the failed-over work
    assert "EXITED mesh=2" in outs[2], outs[2]

    # the merged journal tells the failover story, typed and timed
    events = obs_events.read_journal(obsdir)
    fo = [e for e in events if e["ev"] == "fleet.failover"]
    assert len(fo) == 1 and fo[0]["mesh"] == 1
    assert fo[0]["tickets"] >= 1
    assert fo[0]["error"] == "MeshFailureError"
    assert TTL <= fo[0]["detect_s"] < 20.0
    expired = [e for e in events if e["ev"] == "fleet.lease"
               and e.get("status") == "expired"]
    assert any(e["mesh"] == 1 for e in expired)
    reasons = [e["reason"] for e in events if e["ev"] == "fleet.route"]
    assert reasons.count("rebind") >= 1
    # the injected kill itself was journaled by the dying mesh
    killed = [e for e in events if e["ev"] == "fault"
              and e.get("point") == "fleet.route"]
    assert any(e.get("mode") == "kill" for e in killed)

    # fleet timeline lint-clean through the real pa-obs CLI —
    # schema v6: every fleet.route record carries its trace id
    from pencilarrays_tpu.obs.__main__ import main

    assert main(["lint", obsdir]) == 0
    assert main(["timeline", obsdir]) == 0
    assert main(["trace", obsdir, "-o",
                 str(tmp_path / "trace.json")]) == 0

    # the ISSUE 18 acceptance: pick a ticket that CROSSED the failover
    # (its trace has a rebind record) and reconstruct one causal
    # timeline for it across the router's and both meshes' journals
    from pencilarrays_tpu.obs.requestflow import reconstruct_request

    rebind_traces = sorted({e["trace"] for e in events
                            if e["ev"] == "fleet.route"
                            and e["reason"] == "rebind"})
    assert rebind_traces, "no rebind carried a trace id"
    trace_id = rebind_traces[0]
    rt, _warnings = reconstruct_request(obsdir, trace_id)
    # a SIGKILLed mesh may leave a torn tail — warnings are fine,
    # the reconstruction itself must not be
    assert rt is not None and rt.trace == trace_id
    # the span hops processes: the router's journal (admission, route,
    # failover, rebind) plus the surviving mesh's (admission, dispatch,
    # completion) — at least two ranks in ONE timeline
    assert len(rt.ranks) >= 2, rt.ranks
    assert rt.rebinds >= 1
    assert rt.outcome == "ok"
    evs = [e["ev"] for e in rt.events]
    assert "fleet.route" in evs          # admission → route
    assert "fleet.failover" in evs       # joined via the traces list
    assert "serve.complete" in evs       # exactly-once resolution
    # causal order: the failover re-bind precedes the completion
    assert (evs.index("fleet.failover")
            < max(i for i, e in enumerate(evs)
                  if e == "serve.complete"))

    # the CLI renders it (exit 0), indexes every traced request, and
    # pins exit 1 for an id appearing in no record
    assert main(["request", obsdir, trace_id]) == 0
    assert main(["requests", obsdir]) == 0
    assert main(["request", obsdir, "feedfacedeadbeef"]) == 1


@pytest.mark.slow
def test_double_failover_across_processes(tmp_path):
    """The slow satellite variant: THREE subprocess meshes; the placed
    mesh is SIGKILLed, then the re-bind target is SIGKILLed too — the
    ticket must resolve exactly once on the third."""
    kvroot = str(tmp_path / "kv")
    kv = FileKV(kvroot)
    procs = {m: _spawn(kvroot, m, str(tmp_path)) for m in (1, 2, 3)}
    router = None
    try:
        _await_live(kv, [1, 2, 3])
        router = FleetRouter(kv, ttl=TTL)
        for m in procs:
            router.register_mesh(m)
        u = _host(99, SHAPES["minnow"])
        # submit AND kill the placed mesh before it can poll the
        # request off the wire is racy across processes; instead kill
        # first and let placement route around the corpse twice
        first = router._place("minnow", u.nbytes, None)[0]
        procs[first].send_signal(signal.SIGKILL)
        procs[first].wait(timeout=15)
        t = router.submit("acme", u, name="minnow")
        with router._lock:
            placed = next(iter(router._pending.values())).mesh
        if placed == first:     # placed onto the corpse: must rebind
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                router.pump()
                if router.stats()["dead_meshes"]:
                    break
                time.sleep(0.05)
            with router._lock:
                pend = next(iter(router._pending.values()), None)
            placed = pend.mesh if pend is not None else None
        if placed is not None:
            # second failure: kill whichever mesh now holds the ticket
            procs[placed].send_signal(signal.SIGKILL)
            procs[placed].wait(timeout=15)
        assert router.drain(60.0) == 0
        np.testing.assert_allclose(np.asarray(t.result(1.0)),
                                   np.fft.fftn(u), rtol=1e-3, atol=1e-3)
        stats = router.stats()
        assert stats["completed"] == 1 and stats["failed"] == 0
        assert stats["duplicates"] == 0
        assert len(stats["dead_meshes"]) >= 1
    finally:
        if router is not None:
            router.close()
        for m in procs:
            kv.set(f"pa/fleet/stop/m{m}", "stop")
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
        _reap(procs)
