"""M = N decomposition: every dimension decomposed (reference
``test/pencils.jl:523-542``, the "3D decomposition" testset).  As the
reference notes, the decomposition itself cannot change when all dims
are decomposed — but the permutation can (a pure local relayout), and
arrays/reductions/broadcast/IO must all work."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    gather,
    reshard,
    transpose,
)
from pencilarrays_tpu import ops
from pencilarrays_tpu.io import BinaryDriver, open_file


@pytest.fixture
def topo3(devices):
    return Topology((2, 2, 2))  # 3-D topology: all dims of a 3-D array


def test_fully_decomposed_pencil_and_permutation_change(topo3):
    """The reference's exact scenario: M = N = 3, change only the
    permutation via transpose!, compare distributed arrays."""
    shape = (12, 10, 8)
    pen1 = Pencil(topo3, shape)  # default decomposition: all three dims
    assert pen1.decomposition == (0, 1, 2)
    pen2 = pen1.replace(permutation=Permutation(1, 2, 0))

    rng = np.random.default_rng(0)
    u = (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64)
    u1 = PencilArray.from_global(pen1, u)
    assert u1.pencil.permutation.is_identity()
    u2 = transpose(u1, pen2)  # same decomposition: local relayout only
    assert u2.pencil.permutation == Permutation(1, 2, 0)
    np.testing.assert_array_equal(gather(u2), u)  # logical content equal


def test_fully_decomposed_ragged_and_reductions(topo3):
    shape = (7, 9, 5)  # nothing divides 2 evenly except padding
    pen = Pencil(topo3, shape, (0, 1, 2))
    u = np.random.default_rng(1).standard_normal(shape)
    x = PencilArray.from_global(pen, u)
    np.testing.assert_array_equal(gather(x), u)
    # padding-masked global reductions
    assert np.isclose(float(ops.sum(x)), u.sum())
    assert np.isclose(float(ops.maximum(x)), u.max())
    assert np.isclose(float(ops.mean(x)), u.mean())
    # NumPy-protocol broadcast stays wrapped and exact
    y = np.cos(x)
    np.testing.assert_allclose(gather(y), np.cos(u), rtol=1e-6)


def test_fully_decomposed_2d(devices):
    topo = Topology((2, 4))
    pen = Pencil(topo, (10, 12), (0, 1))  # M = N = 2
    u = np.random.default_rng(2).standard_normal((10, 12))
    x = PencilArray.from_global(pen, u)
    np.testing.assert_array_equal(gather(x), u)


def test_fully_decomposed_transpose_rules(topo3):
    """With all dims decomposed there is no single-slot hop to a
    DIFFERENT decomposition set (any change touches >= 2 slots):
    transpose refuses, reshard (GSPMD) still redistributes."""
    shape = (8, 8, 8)
    pen1 = Pencil(topo3, shape, (0, 1, 2))
    pen_swapped = Pencil(topo3, shape, (1, 0, 2))  # mesh-axis relabel
    u = np.random.default_rng(3).standard_normal(shape)
    x = PencilArray.from_global(pen1, u)
    with pytest.raises(ValueError, match="more than one slot"):
        transpose(x, pen_swapped)
    y = reshard(x, pen_swapped)
    np.testing.assert_array_equal(gather(y), u)


def test_fully_decomposed_io_restart(tmp_path, topo3, devices):
    """Write under M = N, restart under M < N (and back)."""
    shape = (6, 10, 8)
    pen = Pencil(topo3, shape, (0, 1, 2))
    u = np.random.default_rng(4).standard_normal(shape)
    x = PencilArray.from_global(pen, u)
    path = str(tmp_path / "full.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    pen2 = Pencil(Topology((2, 4)), shape, (1, 2))
    with open_file(BinaryDriver(), path, read=True) as f:
        back = f.read("u", pen2)
    np.testing.assert_array_equal(gather(back), u)
    # and the reverse direction: M < N checkpoint into M = N
    with open_file(BinaryDriver(), path, append=True, write=True) as f:
        f.write("v", back)
    with open_file(BinaryDriver(), path, read=True) as f:
        again = f.read("v", pen)
    assert again.pencil == pen
    np.testing.assert_array_equal(gather(again), u)
