"""Seeded randomized parity sweeps.

The reference's suite sweeps hand-picked (method x permutation x
decomposition) grids (``test/transpose.jl:44-91``); this file widens the
net with DETERMINISTIC random configuration draws — shapes (including
primes and barely-ragged extents), topologies, permutations, extra dims,
dtypes, methods and multi-hop chains — each verified against numpy
ground truth.  Seeds are fixed: a failure reproduces exactly.
"""

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import (
    AllToAll,
    Auto,
    Gspmd,
    Pencil,
    PencilArray,
    PencilFFTPlan,
    Permutation,
    Ring,
    Topology,
    gather,
    reshard,
    transpose,
)
from pencilarrays_tpu.ops import reductions

TOPOS = [(8,), (2, 4), (4, 2), (2, 2, 2)]
METHODS = [AllToAll(), Ring(), Gspmd(), Auto(), Auto(latency_bytes=0)]
DTYPES = [np.float32, np.float64, np.complex64]
# extents that stress the ceil-block rule: primes, barely-ragged (P+1),
# divisible, and smaller-than-P
EXTENTS = [5, 7, 8, 9, 11, 12, 13, 16, 17]


def _draw_config(rng, *, ndims=None):
    """One random (topology, shape, decomp, permutation, extra, dtype)."""
    tdims = TOPOS[rng.integers(len(TOPOS))]
    M = len(tdims)
    N = ndims if ndims is not None else int(rng.integers(M + 1, 5))
    shape = tuple(int(EXTENTS[rng.integers(len(EXTENTS))])
                  for _ in range(N))
    decomp = tuple(sorted(rng.choice(N, size=M, replace=False).tolist()))
    perm = (None if rng.random() < 0.4
            else Permutation(tuple(rng.permutation(N).tolist())))
    extra = () if rng.random() < 0.6 else (int(rng.integers(1, 4)),)
    dtype = DTYPES[rng.integers(len(DTYPES))]
    return tdims, shape, decomp, perm, extra, dtype


def _rand_global(rng, shape, extra, dtype):
    vals = rng.standard_normal(shape + extra)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        vals = vals + 1j * rng.standard_normal(shape + extra)
    return vals.astype(dtype)


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_transpose_chain(devices, seed):
    """Random multi-hop chains: every hop matches numpy, the return path
    is bit-identical to the start."""
    rng = np.random.default_rng(1000 + seed)
    tdims, shape, decomp, perm, extra, dtype = _draw_config(rng)
    topo = Topology(tdims)
    N, M = len(shape), len(tdims)
    pen = Pencil(topo, shape, decomp, permutation=perm)
    u = _rand_global(rng, shape, extra, dtype)
    x = PencilArray.from_global(pen, u)
    np.testing.assert_array_equal(gather(x), u)

    hops = []
    cur = pen
    arr = x
    for _ in range(int(rng.integers(1, 4))):
        # draw a single-slot decomposition change (or pure permutation)
        dec = list(cur.decomposition)
        slot = int(rng.integers(M))
        free = [d for d in range(N) if d not in dec]
        if free and rng.random() < 0.8:
            dec[slot] = free[rng.integers(len(free))]
        nperm = (None if rng.random() < 0.4
                 else Permutation(tuple(rng.permutation(N).tolist())))
        nxt = Pencil(topo, shape, tuple(dec), permutation=nperm)
        method = METHODS[rng.integers(len(METHODS))]
        arr = transpose(arr, nxt, method=method)
        np.testing.assert_array_equal(gather(arr), u)
        hops.append((cur, method))
        cur = nxt
    # walk back: bit-identity round trip (test/transpose.jl:60 analog)
    for prev, method in reversed(hops):
        arr = transpose(arr, prev, method=method)
    np.testing.assert_array_equal(gather(arr), u)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_reshard(devices, seed):
    """reshard between two arbitrary random pencils (any number of slots
    may change at once)."""
    rng = np.random.default_rng(2000 + seed)
    tdims, shape, decomp, perm, extra, dtype = _draw_config(rng)
    topo = Topology(tdims)
    N, M = len(shape), len(tdims)
    pen_a = Pencil(topo, shape, decomp, permutation=perm)
    dec_b = tuple(sorted(rng.choice(N, size=M, replace=False).tolist()))
    perm_b = (None if rng.random() < 0.4
              else Permutation(tuple(rng.permutation(N).tolist())))
    pen_b = Pencil(topo, shape, dec_b, permutation=perm_b)
    u = _rand_global(rng, shape, extra, dtype)
    x = PencilArray.from_global(pen_a, u)
    y = reshard(x, pen_b)
    np.testing.assert_array_equal(gather(y), u)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_reductions(devices, seed):
    """Masked distributed reductions on random ragged configs == numpy."""
    rng = np.random.default_rng(3000 + seed)
    tdims, shape, decomp, perm, extra, _ = _draw_config(rng)
    topo = Topology(tdims)
    pen = Pencil(topo, shape, decomp, permutation=perm)
    u = _rand_global(rng, shape, extra, np.float64)
    x = PencilArray.from_global(pen, u)
    np.testing.assert_allclose(float(reductions.sum(x)), u.sum(),
                               rtol=1e-10)
    np.testing.assert_allclose(float(reductions.mean(x)), u.mean(),
                               rtol=1e-10)
    assert float(reductions.minimum(x)) == u.min()
    assert float(reductions.maximum(x)) == u.max()
    np.testing.assert_allclose(
        float(reductions.norm(x)), np.linalg.norm(u.ravel()), rtol=1e-10)
    assert int(reductions.count_nonzero(x)) == np.count_nonzero(u)


_FFT_KINDS = ["fft", "rfft", "dct", "dst", "none"]


def _numpy_reference(u, kinds):
    """Apply the per-dim transforms with numpy/scipy semantics."""
    from scipy import fft as sfft

    out = u.astype(np.complex128 if "fft" in kinds or "rfft" in kinds
                   else np.float64)
    # real kinds act before fft kinds (the plan enforces stage order);
    # numpy applies per-axis transforms commutatively except r2c
    for d, k in enumerate(kinds):
        if k == "dct":
            out = sfft.dct(out.real, axis=d, norm="ortho").astype(out.dtype)
        elif k == "dst":
            out = sfft.dst(out.real, axis=d, norm="ortho").astype(out.dtype)
    for d, k in enumerate(kinds):
        if k == "rfft":
            out = np.fft.rfft(out.real if np.isrealobj(u) else out, axis=d)
        elif k == "fft":
            out = np.fft.fft(out, axis=d)
    return out


def _draw_kinds(rng, N):
    """Random valid transforms tuple: at most one rfft; real-input kinds
    (rfft/dct/dst) must precede any fft dim in stage order; not all
    'none'."""
    for _ in range(64):
        kinds = [str(_FFT_KINDS[rng.integers(len(_FFT_KINDS))])
                 for _ in range(N)]
        if kinds.count("rfft") > 1 or all(k == "none" for k in kinds):
            continue
        complex_seen = False
        ok = True
        for k in kinds:
            if k in ("rfft", "dct", "dst") and complex_seen:
                ok = False
                break
            if k in ("fft", "rfft"):
                complex_seen = True
        if ok:
            return tuple(kinds)
    return ("fft",) * N  # overwhelmingly unlikely fallback


@pytest.mark.parametrize(
    "seed",
    # a few seeds in the default run; the full sweep (~20 s per plan
    # compile) rides the slow marker
    [0] + [pytest.param(s, marks=pytest.mark.slow)
           for s in range(1, 10)])
def test_fuzz_fft_plans(devices, seed):
    """Random per-dim transform tuples on random topologies/shapes match
    the scipy/numpy reference and invert to the input."""
    pytest.importorskip("scipy")
    rng = np.random.default_rng(4000 + seed)
    tdims = TOPOS[rng.integers(len(TOPOS))]  # all use the 8-device mesh
    M = len(tdims)
    N = int(rng.integers(M + 1, 5))
    shape = tuple(int(EXTENTS[rng.integers(len(EXTENTS))])
                  for _ in range(N))
    kinds = _draw_kinds(rng, N)
    topo = Topology(tdims)
    plan = PencilFFTPlan(topo, shape, transforms=kinds, dtype=np.float64)
    u = rng.standard_normal(shape)
    x = PencilArray.from_global(plan.input_pencil, u)
    uh = plan.forward(x)
    np.testing.assert_allclose(gather(uh), _numpy_reference(u, kinds),
                               rtol=1e-8, atol=1e-8)
    back = plan.backward(uh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-8, atol=1e-8)


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_flash_pallas_vs_dense(seed):
    """Randomized flash-kernel parity vs dense attention (interpret
    mode): shapes that stress block padding (sq/skv not multiples of
    block sizes), random offsets, causal on/off, fwd AND grad through
    the hand backward."""
    import jax
    import jax.numpy as jnp

    from pencilarrays_tpu.models.attention import (
        dense_attention, flash_attention)

    rng = np.random.default_rng(1000 + seed)
    sq = int(rng.integers(8, 140))
    skv = int(rng.integers(8, 200))
    h = int(rng.integers(1, 3))
    d = int(rng.choice([8, 16, 32]))
    causal = bool(rng.random() < 0.5)
    q_off = int(rng.integers(0, 12)) if causal else 0
    kv_off = int(rng.integers(0, 8)) if causal else 0
    q = jnp.asarray(rng.standard_normal((sq, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((skv, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((skv, h, d)), jnp.float32)
    # cotangent zeroed on unspecified rows (empty visible-key set)
    rows_ok = np.ones(sq, bool) if not causal else (
        (q_off + np.arange(sq)) >= kv_off)
    ct = jnp.asarray(rng.standard_normal((sq, h, d)) *
                     rows_ok[:, None, None], jnp.float32)

    with jax.default_matmul_precision("float32"):
        ref = dense_attention(q, k, v, causal=causal,
                              q_offset=q_off, kv_offset=kv_off)
        got = flash_attention(q, k, v, causal=causal, impl="pallas",
                              q_offset=q_off, kv_offset=kv_off)
        np.testing.assert_allclose(np.asarray(got)[rows_ok],
                                   np.asarray(ref)[rows_ok],
                                   atol=1e-5, rtol=1e-5)

        def loss(impl):
            def f(q_, k_, v_):
                return jnp.sum(flash_attention(
                    q_, k_, v_, causal=causal, impl=impl,
                    q_offset=q_off, kv_offset=kv_off) * ct)
            return f

        gp = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)
