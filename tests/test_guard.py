"""Runtime integrity guard: SDC probes, watchdog, detect-and-recover.

The contracts under test (ISSUE 5 acceptance):

* with ``PENCILARRAYS_TPU_GUARD`` unset, hop/reshard dispatch routes
  through the UNMODIFIED pre-guard executables and the hop jaxpr
  carries no probe ops (byte-identical disabled path, test-pinned);
* with it on, the invariant probes ride the SAME jitted program as the
  exchange (jaxpr-pinned: probe reductions and the collective appear in
  one jaxpr; exactly one executable call per hop) and the hop output is
  bit-identical to the unguarded path;
* a fault-injected corrupted exchange (``hop.exchange:corrupt``) raises
  typed ``IntegrityError`` + journals ``guard.sdc`` + writes a readable
  crash bundle — across AllToAll / Ring / Pipelined and routed
  reshards — while the SAME drill with the guard off flows through as
  silent garbage (the failure mode the guard exists for);
* the watchdog fires on an artificially-held lock: crash bundle written
  by the monitor thread, typed ``HangTimeoutError`` raised;
* ``guarded_step`` retries on ``IntegrityError`` and escalates to a
  ``CheckpointManager.latest_valid()`` restore, recovering
  bit-identically, with the full timeline journaled.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import guard, obs
from pencilarrays_tpu.guard import HangTimeoutError, IntegrityError
from pencilarrays_tpu.guard import integrity as gi
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.parallel import transpositions as tr
from pencilarrays_tpu.resilience import CheckpointManager, RetryPolicy, faults


@pytest.fixture(autouse=True)
def _clean_guard(monkeypatch):
    """Every test starts with guard + obs disabled and faults cleared."""
    for var in (guard.ENV_VAR, guard.DIR_VAR, guard.TIMEOUT_VAR,
                guard.RTOL_VAR, guard.FINITE_VAR, obs.ENV_VAR,
                faults.ENV_VAR):
        monkeypatch.delenv(var, raising=False)
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _load_json(path):
    with open(path) as f:
        return json.load(f)


def _read_text(path):
    with open(path) as f:
        return f.read()


def _mk(shape=(11, 9, 13), dims=(2, 4), seed=0):
    topo = pa.Topology(dims)
    pen_x = pa.Pencil(topo, shape, (1, 2))
    pen_y = pa.Pencil(topo, shape, (0, 2))
    truth = np.random.default_rng(seed).standard_normal(shape)
    return pen_x, pen_y, truth, pa.PencilArray.from_global(pen_x, truth)


# ---------------------------------------------------------------------------
# disabled path: byte-identical executables, no probe ops
# ---------------------------------------------------------------------------


def test_disabled_path_uses_unguarded_executable(monkeypatch):
    """Guard off: transpose() must route through the untouched
    ``_compiled_transpose`` (the pre-guard executable) and never build a
    guarded one."""
    assert not guard.enabled()
    pen_x, pen_y, truth, u = _mk()
    calls = []
    orig = tr._dispatch_guarded_hop
    monkeypatch.setattr(tr, "_dispatch_guarded_hop",
                        lambda *a, **k: calls.append(1) or orig(*a, **k))
    out = pa.transpose(u, pen_y)
    assert calls == []
    assert np.array_equal(pa.gather(out), truth)


def test_disabled_hop_jaxpr_has_no_probe_ops():
    """The jaxpr of the guard-off hop is the raw hop body — no reduce
    ops beyond what the exchange itself needs — while the guarded
    program contains the probe reductions IN THE SAME jaxpr as the
    collective (no extra dispatch)."""
    import jax

    pen_x, pen_y, _, u = _mk(shape=(8, 8, 8))
    R = tr.assert_compatible(pen_x, pen_y)
    plain = tr._hop_body(pen_x, pen_y, R, 0, tr.AllToAll())
    jp_plain = str(jax.make_jaxpr(plain)(u.data))
    assert "all_to_all" in jp_plain
    # the plain hop body is pure movement: no probe-style reductions
    assert "reduce_sum" not in jp_plain

    from pencilarrays_tpu.guard import integrity as _gi

    def guarded(data):
        pre = _gi.probe_stats(data)
        out = plain(data)
        return out, pre, _gi.probe_stats(out)

    jp_guarded = str(jax.make_jaxpr(guarded)(u.data))
    assert "all_to_all" in jp_guarded      # same program...
    assert "reduce_sum" in jp_guarded      # ...with the probes riding it


def test_gate_re_read_on_change(monkeypatch, tmp_path):
    """Workers arm the guard after import (the faults.py contract)."""
    assert not guard.enabled()
    monkeypatch.setenv(guard.ENV_VAR, str(tmp_path / "b"))
    assert guard.enabled()
    assert guard.bundle_dir() == str(tmp_path / "b")
    monkeypatch.setenv(guard.ENV_VAR, "0")
    assert not guard.enabled()


# ---------------------------------------------------------------------------
# guarded path: bit-identity, single program
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("method", [tr.AllToAll(), tr.Ring(),
                                    tr.Pipelined(chunks=2)])
def test_guarded_hop_bit_identical(method, tmp_path):
    pen_x, pen_y, truth, u = _mk()
    base = np.asarray(pa.gather(pa.transpose(u, pen_y, method=method)))
    guard.enable(str(tmp_path / "bundles"))
    out = pa.transpose(u, pen_y, method=method)
    assert np.array_equal(np.asarray(pa.gather(out)), base)
    assert np.array_equal(base, truth)


def test_guarded_hop_single_dispatch(monkeypatch, tmp_path):
    """Probes ride the hop's own program: exactly one guarded
    executable call per transpose, zero plain-executable calls."""
    pen_x, pen_y, truth, u = _mk()
    guard.enable(str(tmp_path / "bundles"))
    guarded_calls, plain_calls = [], []
    orig_g = tr._compiled_guarded_transpose
    orig_p = tr._compiled_transpose

    def spy_g(*a, **k):
        fn = orig_g(*a, **k)
        return lambda *d: guarded_calls.append(1) or fn(*d)

    def spy_p(*a, **k):
        fn = orig_p(*a, **k)
        return lambda *d: plain_calls.append(1) or fn(*d)

    monkeypatch.setattr(tr, "_compiled_guarded_transpose", spy_g)
    monkeypatch.setattr(tr, "_compiled_transpose", spy_p)
    pa.transpose(u, pen_y)
    assert guarded_calls == [1]
    assert plain_calls == []


def test_guarded_exact_dtype_bit_for_bit(tmp_path):
    """Integer hops compare EXACTLY (wrapping sums are
    order-independent), so the guard tolerates zero deviation."""
    pen_x, pen_y, _, _ = _mk()
    rng = np.random.default_rng(3)
    vals = rng.integers(-2 ** 30, 2 ** 30, size=(11, 9, 13),
                        dtype=np.int32)
    u = pa.PencilArray.from_global(pen_x, vals)
    guard.enable(str(tmp_path / "bundles"))
    out = pa.transpose(u, pen_y)
    assert np.array_equal(np.asarray(pa.gather(out)), vals)


def test_guarded_passes_nan_through(tmp_path):
    """NaN already in the INPUT is data, not corruption: the probe pair
    matches (NaN on both sides) and the hop completes."""
    pen_x, pen_y, truth, _ = _mk()
    vals = truth.copy()
    vals[0, 0, 0] = np.nan
    u = pa.PencilArray.from_global(pen_x, vals)
    guard.enable(str(tmp_path / "bundles"))
    out = np.asarray(pa.gather(pa.transpose(u, pen_y)))
    assert np.isnan(out[0, 0, 0]) and np.array_equal(
        out[1:], vals[1:], equal_nan=True)


# ---------------------------------------------------------------------------
# SDC drills: corrupt injection -> typed error (guarded) / garbage (not)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("method", [tr.AllToAll(), tr.Ring(),
                                    tr.Pipelined(chunks=2)])
def test_corrupt_exchange_raises_typed_error(method, tmp_path):
    pen_x, pen_y, truth, u = _mk()
    guard.enable(str(tmp_path / "bundles"))
    with faults.active("hop.exchange:corrupt"):
        with pytest.raises(IntegrityError) as ei:
            pa.transpose(u, pen_y, method=method)
    e = ei.value
    assert e.kind == "sum" and e.hop and e.predicted and e.observed
    # the crash bundle is readable: MANIFEST.json marks completeness
    assert e.bundle and os.path.isdir(e.bundle)
    mf = _load_json(os.path.join(e.bundle, "MANIFEST.json"))
    assert mf["reason"] == "sdc"
    assert os.path.exists(os.path.join(e.bundle, "stacks.txt"))
    _load_json(os.path.join(e.bundle, "metrics.json"))


@pytest.mark.chaos
def test_corrupt_exchange_unguarded_is_silent_garbage(tmp_path):
    """The motivation, pinned: the SAME drill with the guard off flows
    through undetected — wrong data, no error."""
    pen_x, pen_y, truth, u = _mk()
    assert not guard.enabled()
    with faults.active("hop.exchange:corrupt"):
        out = np.asarray(pa.gather(pa.transpose(u, pen_y)))
    assert not np.array_equal(out, truth)
    assert np.isnan(out).any()


@pytest.mark.chaos
def test_corrupt_counter_addressing(tmp_path):
    """``@nth`` addresses the nth DISPATCH: hop 1 clean, hop 2
    corrupted — deterministic replay, the faults.py contract."""
    pen_x, pen_y, truth, u = _mk()
    guard.enable(str(tmp_path / "bundles"))
    with faults.active("hop.exchange:corrupt@2"):
        out1 = pa.transpose(u, pen_y)           # hit 1: clean
        assert np.array_equal(np.asarray(pa.gather(out1)), truth)
        with pytest.raises(IntegrityError):
            pa.transpose(u, pen_y)              # hit 2: corrupted


@pytest.mark.chaos
def test_corrupt_routed_reshard_raises_typed_error(tmp_path):
    """Multi-slot reshard (the routed chain, or its GSPMD fallback) is
    probed per hop: injected corruption surfaces as IntegrityError
    naming the poisoned hop, clean runs stay bit-identical."""
    topo = pa.Topology((2, 4))
    shape = (12, 16, 8)
    src = pa.Pencil(topo, shape, (1, 2))
    dst = pa.Pencil(topo, shape, (2, 0))
    truth = np.random.default_rng(5).standard_normal(shape)
    u = pa.PencilArray.from_global(src, truth)
    base = np.asarray(pa.gather(pa.reshard(u, dst)))
    assert np.array_equal(base, truth)
    guard.enable(str(tmp_path / "bundles"))
    out = pa.reshard(u, dst)
    assert np.array_equal(np.asarray(pa.gather(out)), truth)
    with faults.active("hop.exchange:corrupt"):
        with pytest.raises(IntegrityError) as ei:
            pa.reshard(u, dst)
    assert ei.value.kind == "sum"


@pytest.mark.chaos
def test_corrupt_local_permute_hop_raises_typed_error(tmp_path):
    """A local (R=None) hop — same decomposition, different memory
    order — is pure movement too: with the guard on, the corrupt drill
    must be a typed error there as well, never garbage."""
    topo = pa.Topology((2, 4))
    shape = (11, 9, 13)
    pen_a = pa.Pencil(topo, shape, (1, 2))
    pen_b = pa.Pencil(topo, shape, (1, 2),
                      permutation=pa.Permutation(2, 0, 1))
    truth = np.random.default_rng(9).standard_normal(shape)
    u = pa.PencilArray.from_global(pen_a, truth)
    guard.enable(str(tmp_path / "bundles"))
    out = pa.transpose(u, pen_b)    # clean local permute passes
    assert np.array_equal(np.asarray(pa.gather(out)), truth)
    with faults.active("hop.exchange:corrupt"):
        with pytest.raises(IntegrityError):
            pa.transpose(u, pen_b)


@pytest.mark.chaos
def test_corrupt_reshard_fires_same_counter_guard_on_or_off(tmp_path):
    """The hop.exchange hit counter must address the same routed
    dispatches whether the guard is armed or not (deterministic
    replay): guard off -> silent garbage on the SAME dispatch the
    guarded run detects."""
    topo = pa.Topology((2, 4))
    shape = (12, 16, 8)
    src = pa.Pencil(topo, shape, (1, 2))
    dst = pa.Pencil(topo, shape, (2, 0))
    truth = np.random.default_rng(5).standard_normal(shape)
    u = pa.PencilArray.from_global(src, truth)
    assert not guard.enabled()
    with faults.active("hop.exchange:corrupt@1"):
        bad = np.asarray(pa.gather(pa.reshard(u, dst)))
    assert not np.array_equal(bad, truth) and np.isnan(bad).any()


def test_corrupt_block_deterministic():
    """The poke itself: counter-addressed, NaN for floats, sign-bit
    flip for ints, same index -> same result."""
    import jax.numpy as jnp

    x = jnp.arange(24, dtype=jnp.float32).reshape(4, 6)
    a = np.asarray(gi.corrupt_eager(x, 7))
    b = np.asarray(gi.corrupt_eager(x, 7))
    assert np.array_equal(a, b, equal_nan=True)
    assert np.isnan(a.ravel()[7]) and np.isfinite(np.delete(a.ravel(), 7)).all()
    xi = jnp.arange(24, dtype=jnp.int32).reshape(4, 6)
    ai = np.asarray(gi.corrupt_eager(xi, 3))
    assert ai.ravel()[3] != 3 and (np.delete(ai.ravel(), 3)
                                   == np.delete(np.arange(24), 3)).all()


def test_corrupt_mode_parse():
    (r,) = faults.parse("hop.exchange:corrupt@2")
    assert r.mode == "corrupt" and r.first == 2 and r.times is None
    (r2,) = faults.parse("ckpt.restore:corrupt*3")
    assert r2.times == 3
    with pytest.raises(ValueError):
        faults.parse("hop.exchange:explode")


@pytest.mark.chaos
def test_ckpt_restore_corrupt_drill(tmp_path):
    """The ``ckpt.restore`` corrupt point pokes the restored dataset
    deterministically (post-verification in-flight corruption): the
    restored array differs from the committed truth at exactly the
    addressed element."""
    pen = pa.Pencil(pa.Topology((8,)), (11, 9, 13), (1,))
    truth = np.random.default_rng(7).standard_normal((11, 9, 13))
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    mgr.save(1, {"u": pa.PencilArray.from_global(pen, truth)})
    clean = np.asarray(pa.gather(mgr.restore().read("u", pen)))
    assert np.array_equal(clean, truth)
    with faults.active("ckpt.restore:corrupt"):
        poked = np.asarray(pa.gather(mgr.restore().read("u", pen)))
    assert not np.array_equal(poked, truth)
    assert np.isnan(poked).sum() == 1


# ---------------------------------------------------------------------------
# finiteness tap
# ---------------------------------------------------------------------------


def test_finite_tap_catches_nonfinite_birth(monkeypatch, tmp_path):
    """The "NaN born mid-FFT" detector: finite input, nonfinite output
    of a transform boundary -> typed IntegrityError (here driven by an
    honest f32 overflow: the DC term of an FFT of huge values)."""
    import jax.numpy as jnp

    import jax

    monkeypatch.setenv(guard.FINITE_VAR, "1")   # sample every dispatch
    topo = pa.Topology((1,), devices=jax.devices()[:1])
    plan = pa.PencilFFTPlan(topo, (16, 16, 16), real=True,
                            dtype=jnp.float32)
    u = plan.allocate_input()
    big = pa.PencilArray(u.pencil,
                         jnp.full(u.data.shape, 1e37, jnp.float32))
    guard.enable(str(tmp_path / "bundles"))
    with pytest.raises(IntegrityError) as ei:
        plan.forward(big)
    assert ei.value.kind == "nonfinite"
    # finite input passes untouched
    ok = pa.PencilArray(u.pencil, jnp.ones(u.data.shape, jnp.float32))
    plan.forward(ok)


def test_finite_tap_sampling_counter(monkeypatch):
    monkeypatch.setenv(guard.FINITE_VAR, "3")
    ticks = [guard.finite_tick() for _ in range(6)]
    assert ticks == [False, False, True, False, False, True]
    monkeypatch.delenv(guard.FINITE_VAR)
    assert guard.finite_tick() is False


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_watchdog_fires_on_held_lock(tmp_path):
    """The deliberately-wedged 'collective': a lock that is never
    released.  The monitor fires at the deadline, writes a complete
    bundle WHILE the section is still stuck, then unblocks us with the
    typed error carrying the bundle path."""
    guard.enable(str(tmp_path / "bundles"))
    held = threading.Lock()
    held.acquire()
    with pytest.raises(HangTimeoutError) as ei:
        with guard.watchdog("test-hold", timeout=0.4, kind="test"):
            held.acquire()
    e = ei.value
    assert e.label == "test-hold" and e.timeout_s == pytest.approx(0.4)
    assert e.bundle and os.path.isdir(e.bundle)
    mf = _load_json(os.path.join(e.bundle, "MANIFEST.json"))
    assert mf["reason"] == "hang" and mf["label"] == "test-hold"
    assert mf["artifacts"]["stacks"] == "ok"
    stacks = _read_text(os.path.join(e.bundle, "stacks.txt"))
    assert "test_watchdog_fires_on_held_lock" in stacks
    _load_json(os.path.join(e.bundle, "metrics.json"))
    from pencilarrays_tpu.guard.watchdog import active_count

    assert active_count() == 0


def test_watchdog_noop_when_disabled():
    assert not guard.enabled()
    held = threading.Lock()
    with guard.watchdog("never-armed", timeout=0.05):
        import time

        time.sleep(0.15)   # would have fired if armed
    from pencilarrays_tpu.guard.watchdog import active_count

    assert active_count() == 0


def test_watchdog_completes_under_deadline(tmp_path):
    guard.enable(str(tmp_path / "bundles"))
    with guard.watchdog("fast", timeout=30.0):
        x = sum(range(100))
    assert x == 4950
    assert not os.path.exists(str(tmp_path / "bundles"))


@pytest.mark.chaos
def test_watchdog_wraps_distributed_initialize(tmp_path, monkeypatch):
    """A wedged coordinator: connect blocks past the deadline -> crash
    bundle + typed HangTimeoutError (surfaced through the retry policy
    as the attempt's failure)."""
    import time

    import jax

    from pencilarrays_tpu.parallel import distributed
    from pencilarrays_tpu.resilience.errors import RetryDeadlineExceeded

    assert not distributed.is_initialized()
    guard.enable(str(tmp_path / "bundles"))
    monkeypatch.setenv(guard.TIMEOUT_VAR, "0.4")
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: time.sleep(30))
    with pytest.raises((HangTimeoutError, RetryDeadlineExceeded)):
        distributed.initialize(
            "127.0.0.1:1", 2, 0,
            retry=RetryPolicy(max_attempts=1, deadline=5.0))
    assert not distributed.is_initialized()
    bundles = os.listdir(str(tmp_path / "bundles"))
    assert len(bundles) == 1


# ---------------------------------------------------------------------------
# guarded_step: detect-and-recover
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_guarded_step_retries_then_succeeds(tmp_path):
    """Transient corruption: retry alone recovers (no checkpoint
    needed), result bit-identical."""
    pen_x, pen_y, truth, u = _mk()
    guard.enable(str(tmp_path / "bundles"))
    with faults.active("hop.exchange:corrupt*1"):
        out = guard.guarded_step(
            lambda: pa.transpose(u, pen_y),
            retry=RetryPolicy(max_attempts=3, base_delay=0.01),
            label="retry-drill")
    assert np.array_equal(np.asarray(pa.gather(out)), truth)


@pytest.mark.chaos
def test_guarded_step_escalates_to_checkpoint_restore(tmp_path):
    """Attempts exhausted -> restore from the last committed checkpoint
    -> bit-identical result; the journal carries the full
    error/retry/restore/recovered timeline (schema-clean)."""
    obs.enable(str(tmp_path / "obs"))
    guard.enable(str(tmp_path / "bundles"))
    pen_x, pen_y, truth, u = _mk()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    state = {"u": u}
    mgr.save(1, {"u": u})
    # simulate post-crash state divergence: in-memory state is wrong,
    # only the checkpoint holds the truth
    state["u"] = pa.PencilArray.from_global(
        pen_x, truth + 1000.0)

    def step():
        return pa.transpose(state["u"], pen_y)

    def restore(ckpt):
        state["u"] = ckpt.read("u", pen_x)

    # attempts 1-2 hit corruption; escalation restores; attempt 3 clean
    with faults.active("hop.exchange:corrupt*2"):
        out = guard.guarded_step(
            step, ckpt_mgr=mgr, restore=restore,
            retry=RetryPolicy(max_attempts=2, base_delay=0.01),
            label="escalate-drill")
    assert np.array_equal(np.asarray(pa.gather(out)), truth)
    events = obs.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    stages = [e["stage"] for e in events if e["ev"] == "guard.recover"]
    assert stages[0] == "error"
    assert "restore" in stages and stages[-1] == "recovered"
    assert {e["ev"] for e in events} >= {"guard.sdc", "guard.recover",
                                         "ckpt.restore"}


@pytest.mark.chaos
def test_guarded_step_reraises_without_checkpoint(tmp_path):
    pen_x, pen_y, truth, u = _mk()
    guard.enable(str(tmp_path / "bundles"))
    with faults.active("hop.exchange:corrupt"):
        with pytest.raises(IntegrityError):
            guard.guarded_step(
                lambda: pa.transpose(u, pen_y),
                retry=RetryPolicy(max_attempts=2, base_delay=0.01),
                label="no-ckpt-drill")


def test_guarded_step_passthrough_other_errors(tmp_path):
    guard.enable(str(tmp_path / "bundles"))
    with pytest.raises(ZeroDivisionError):
        guard.guarded_step(lambda: 1 // 0,
                           retry=RetryPolicy(max_attempts=3))


# ---------------------------------------------------------------------------
# guarded_step: the deadline edge (recover.py's escalate-now branch)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_guarded_step_deadline_escalates_immediately(tmp_path):
    """When the NEXT backoff delay would overshoot ``policy.deadline``
    the ladder must escalate to the checkpoint restore NOW — not sleep
    through a delay it already knows is over budget.  Pinned: no
    ``retry`` stage is journaled, no backoff sleep happens (wall-clock
    bound far below the 10 s delay), and the restore still recovers."""
    obs.enable(str(tmp_path / "obs"))
    guard.enable(str(tmp_path / "bundles"))
    pen_x, pen_y, truth, u = _mk()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    state = {"u": u}
    mgr.save(1, {"u": u})
    state["u"] = pa.PencilArray.from_global(pen_x, truth + 1000.0)
    calls = {"n": 0}

    def step():
        calls["n"] += 1
        return pa.transpose(state["u"], pen_y)

    def restore(ckpt):
        state["u"] = ckpt.read("u", pen_x)

    t0 = time.monotonic()
    # 5 attempts of budget, but the first backoff (10 s) cannot fit the
    # 0.05 s deadline: exactly ONE failing attempt, then escalate (the
    # post-restore rerun is hit 2, past the rule's one firing)
    with faults.active("hop.exchange:corrupt*1"):
        out = guard.guarded_step(
            step, ckpt_mgr=mgr, restore=restore,
            retry=RetryPolicy(max_attempts=5, base_delay=10.0,
                              max_delay=10.0, deadline=0.05),
            label="deadline-drill")
    assert time.monotonic() - t0 < 8.0, "the ladder slept through a " \
        "backoff it knew exceeded the deadline"
    assert calls["n"] == 2          # one failed attempt + the post-restore run
    assert np.array_equal(np.asarray(pa.gather(out)), truth)
    events = obs.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    stages = [e["stage"] for e in events if e["ev"] == "guard.recover"]
    assert stages == ["error", "restore", "recovered"], stages


@pytest.mark.chaos
def test_guarded_step_deadline_reraise_without_checkpoint(tmp_path):
    """Same edge with no escalation rung: re-raise immediately instead
    of sleeping out attempts the deadline cannot fund."""
    guard.enable(str(tmp_path / "bundles"))
    pen_x, pen_y, truth, u = _mk()
    t0 = time.monotonic()
    with faults.active("hop.exchange:corrupt*5"):
        with pytest.raises(IntegrityError):
            guard.guarded_step(
                lambda: pa.transpose(u, pen_y),
                retry=RetryPolicy(max_attempts=5, base_delay=10.0,
                                  max_delay=10.0, deadline=0.05),
                label="deadline-reraise")
    assert time.monotonic() - t0 < 8.0


def test_guarded_step_deadline_accounts_for_jitter(tmp_path, monkeypatch):
    """The escalate-now decision uses the ACTUAL jittered delay, so a
    jitter draw that overshoots the deadline escalates while a draw
    that fits retries — delay_for's jitter stays inside the deadline
    accounting, never silently beyond it."""
    import random as _random

    guard.enable(str(tmp_path / "bundles"))
    pen_x, pen_y, truth, u = _mk()
    # base 1.0s, jitter 0.25 -> delay in [0.75, 1.25]; deadline 1.2
    policy = RetryPolicy(max_attempts=2, base_delay=1.0, max_delay=1.0,
                         deadline=1.2, jitter=0.25)
    # max-jitter draw (random()=1 -> factor 1.25): 1.25 > 1.2 deadline,
    # must escalate without sleeping
    monkeypatch.setattr(_random, "random", lambda: 1.0)
    t0 = time.monotonic()
    with faults.active("hop.exchange:corrupt*1"):
        with pytest.raises(IntegrityError):
            guard.guarded_step(lambda: pa.transpose(u, pen_y),
                               retry=policy, label="jitter-over")
    assert time.monotonic() - t0 < 0.7
    faults.reset_counters()
    # min-jitter draw (random()=0 -> factor 0.75): 0.75 <= 1.2, the
    # retry happens and recovers
    monkeypatch.setattr(_random, "random", lambda: 0.0)
    with faults.active("hop.exchange:corrupt*1"):
        out = guard.guarded_step(lambda: pa.transpose(u, pen_y),
                                 retry=policy, label="jitter-under")
    assert np.array_equal(np.asarray(pa.gather(out)), truth)


def test_delay_for_jitter_bounds():
    """delay_for stays inside [nominal*(1-jitter), nominal*(1+jitter)]
    with the exponential curve capped at max_delay — THE bound the
    deadline accounting above relies on."""
    policy = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.25)
    for attempt in range(1, 9):
        nominal = min(0.1 * 2 ** (attempt - 1), 1.0)
        for _ in range(50):
            d = policy.delay_for(attempt)
            assert nominal * 0.75 - 1e-12 <= d <= nominal * 1.25 + 1e-12


# ---------------------------------------------------------------------------
# journaling / metrics
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_guard_events_schema_and_counters(tmp_path):
    obs.enable(str(tmp_path / "obs"))
    guard.enable(str(tmp_path / "bundles"))
    pen_x, pen_y, truth, u = _mk()
    pa.transpose(u, pen_y)                       # ok check
    with faults.active("hop.exchange:corrupt"):
        with pytest.raises(IntegrityError):
            pa.transpose(u, pen_y)               # sdc + bundle
    events = obs.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    kinds = {e["ev"] for e in events}
    assert {"guard.sdc", "guard.bundle"} <= kinds
    snap = obs.snapshot()
    checks = {k: v for k, v in snap["counters"].items()
              if k.startswith("guard.checks")}
    assert checks.get("guard.checks{outcome=ok}", 0) >= 1
    assert checks.get("guard.checks{outcome=sum}", 0) >= 1


def test_probe_tolerance_semantics():
    """Unit coverage of the host-side compare: exact dtypes exact,
    float pairs within tolerance pass, NaN birth fails, matching NaNs
    pass."""
    import jax.numpy as jnp

    x = jnp.asarray(np.random.default_rng(0).standard_normal(1000),
                    jnp.float64)
    p = gi.probe_stats(x)
    ok, kind = gi.probes_match(p, p, 1000, np.float64)
    assert ok
    # a perturbed sum within rounding tolerance still passes
    q = np.asarray(p).copy()
    q[0] += abs(q[2]) * 1e-14
    assert gi.probes_match(p, q, 1000, np.float64)[0]
    # beyond tolerance fails
    q2 = np.asarray(p).copy()
    q2[0] += abs(q2[2]) * 1e-3
    assert not gi.probes_match(p, q2, 1000, np.float64)[0]
    # NaN birth fails; NaN on both sides passes
    qn = np.asarray(p).copy()
    qn[0] = np.nan
    assert not gi.probes_match(p, qn, 1000, np.float64)[0]
    assert gi.probes_match(qn, qn, 1000, np.float64)[0]
    # exact dtype: any deviation fails
    pi = gi.probe_stats(jnp.arange(10, dtype=jnp.int32))
    qi = np.asarray(pi).copy()
    qi[0] += 1.0
    assert not gi.probes_match(pi, qi, 10, np.int32)[0]


def test_bundle_contains_plan_fingerprints(tmp_path):
    """Plans built while the guard is armed ride every later bundle."""
    import jax.numpy as jnp

    guard.enable(str(tmp_path / "bundles"))
    topo = pa.Topology((2, 4))
    pa.PencilFFTPlan(topo, (8, 8, 8), dtype=jnp.complex64)
    path = guard.write_crash_bundle("test", "unit")
    plans = _load_json(os.path.join(path, "plans.json"))
    assert any(p["kind"] == "fft_plan" for p in plans)
    mf = _load_json(os.path.join(path, "MANIFEST.json"))
    assert mf["reason"] == "test"
