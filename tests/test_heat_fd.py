"""HeatFD model: exact agreement with a NumPy reference of the same
scheme, cross-validation against the exact spectral integrator,
decomposition independence, and a neighbor-only collective profile."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu.models import DiffusionSpectral, HeatFD
from pencilarrays_tpu.utils.hlo import collective_stats


def _np_lap(g, spacing):
    return sum((np.roll(g, -1, d) - 2 * g + np.roll(g, 1, d)) / h ** 2
               for d, h in enumerate(spacing))


def _np_step(g, dt, kappa, spacing):
    mid = g + 0.5 * dt * kappa * _np_lap(g, spacing)
    return g + dt * kappa * _np_lap(mid, spacing)


def test_matches_numpy_reference(devices):
    topo = pa.Topology((4, 2), devices=devices)
    model = HeatFD(topo, (12, 10, 8), kappa=0.7, dtype=jnp.float64)
    g = np.random.default_rng(0).standard_normal((12, 10, 8))
    u = model.from_global(g)
    dt = model.stable_dt()
    for _ in range(3):
        u = model.step(u, dt)
        g = _np_step(g, dt, model.kappa, model.spacing)
    np.testing.assert_allclose(np.asarray(pa.gather(u)), g,
                               atol=1e-12, rtol=1e-12)


@pytest.mark.slow  # ~15 s: FD vs spectral integration cross-check
def test_cross_validates_spectral(devices):
    """FD vs the exact spectral propagator on a smooth low-mode field:
    the FD error is O(h^2 + dt^2) and must shrink ~4x when the grid
    refines 16 -> 32 (same final time)."""
    topo = pa.Topology((4,), devices=devices[:4])
    errs = []
    for n in (16, 32):
        fd = HeatFD(topo, (n, n, n), kappa=0.05, dtype=jnp.float64)
        sp = DiffusionSpectral(topo, (n, n, n), kappa=0.05,
                               dtype=jnp.float64)
        x = np.arange(n) * 2 * np.pi / n
        g = (np.sin(x)[:, None, None] * np.cos(x)[None, :, None]
             * np.ones(n)[None, None, :])
        u = fd.from_global(g)
        t_final, nsteps = 0.5, 64
        dt = t_final / nsteps
        assert dt < fd.stable_dt(1.0)
        for _ in range(nsteps):
            u = fd.step(u, dt)
        # spectral: exact propagator on the same initial condition
        u0 = pa.PencilArray.from_global(sp.plan.input_pencil, g)
        exact = sp.solve(u0, t_final)
        err = np.abs(np.asarray(pa.gather(u))
                     - np.asarray(pa.gather(exact))).max()
        errs.append(err)
    assert errs[1] < errs[0] / 3.0


def test_decomposition_independent(devices):
    g = np.random.default_rng(1).standard_normal((8, 12, 10))
    outs = []
    for dims, decomp in [((8,), (0,)), ((4, 2), (1, 2)), ((2, 4), (0, 2))]:
        topo = pa.Topology(dims, devices=devices[:int(np.prod(dims))])
        m = HeatFD(topo, (8, 12, 10), kappa=0.3, decomp_dims=decomp,
                   dtype=jnp.float64)
        u = m.from_global(g)
        dt = m.stable_dt()
        for _ in range(2):
            u = m.step(u, dt)
        outs.append(np.asarray(pa.gather(u)))
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-12)
    np.testing.assert_allclose(outs[0], outs[2], atol=1e-12)


def test_neighbor_only_collectives(devices):
    """A heat step is pure halo exchange: collective-permutes only —
    no all-to-all, no all-gather, no all-reduce."""
    topo = pa.Topology((4, 2), devices=devices)
    model = HeatFD(topo, (16, 16, 8), kappa=1.0)
    u = model.allocate()
    dt = model.stable_dt()
    hlo = jax.jit(lambda d: model.step(
        pa.PencilArray(model.pencil, d), dt).data) \
        .lower(u.data).compile().as_text()
    stats = collective_stats(hlo)
    assert set(stats) <= {"collective-permute"}, stats


def test_zero_boundary_decays(devices):
    """Zero (absorbing) boundaries drain the box: energy strictly
    decreases and no wraparound feeds back."""
    topo = pa.Topology((4,), devices=devices[:4])
    m = HeatFD(topo, (16, 16, 16), kappa=1.0, boundary="zero",
               dtype=jnp.float64)
    g = np.zeros((16, 16, 16))
    g[8, 8, 8] = 1.0
    u = m.from_global(g)
    dt = m.stable_dt()
    e0 = float(pa.ops.norm(u))
    for _ in range(5):
        u = m.step(u, dt)
    e1 = float(pa.ops.norm(u))
    assert e1 < e0
    assert bool(jnp.isfinite(u.data).all())
