"""Compiled-program regression guards: the transpose must lower to exactly
ONE all-to-all on the differing mesh axis — no stray collectives, no
accidental resharding — and FFT plans must not smuggle extra exchanges.

This is the TPU analog of the reference asserting zero allocations in hot
loops (``test/broadcast.jl:38-40``): the property checked is about the
*compiled artifact*, not the numerics.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Ring,
    Pencil,
    PencilArray,
    PencilFFTPlan,
    Permutation,
    Topology,
    transpose,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def hlo_of(fn, *args):
    return jax.jit(fn).lower(*args).compile().as_text()


def count_collectives(hlo: str):
    # count opcode applications ("... all-to-all(args)"), not name
    # references like get-tuple-element(%all-to-all)
    return {
        name: len(re.findall(rf" {name}\(", hlo))
        for name in ("all-to-all", "all-gather", "all-reduce",
                     "collective-permute")
    }


def test_single_all_to_all_per_transpose(topo):
    shape = (16, 16, 16)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2), permutation=Permutation(1, 0, 2))
    x = PencilArray.zeros(pen_x)

    def f(a):
        return transpose(a, pen_y, method=AllToAll()).data

    c = count_collectives(hlo_of(f, x))
    assert c["all-to-all"] == 1, c
    assert c["all-gather"] == 0 and c["collective-permute"] == 0, c


def test_ring_method_ppermute_rounds(topo):
    """Ring() lowers to P-1 collective-permutes and no all-to-all."""
    shape = (16, 16, 16)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2))   # exchange over p1 (P=2) -> 1 round
    pen_z = Pencil(topo, shape, (1, 0))   # exchange over p2 (P=4) -> 3 rounds
    x = PencilArray.zeros(pen_x)
    c = count_collectives(hlo_of(
        lambda a: transpose(a, pen_y, method=Ring()).data, x))
    assert c["collective-permute"] == 1 and c["all-to-all"] == 0, c
    c = count_collectives(hlo_of(
        lambda a: transpose(a, pen_z, method=Ring()).data, x))
    assert c["collective-permute"] == 3 and c["all-to-all"] == 0, c


def test_ragged_transpose_still_one_exchange(topo):
    """Padding must be handled by local pad/slice, not extra collectives."""
    shape = (13, 11, 9)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2))
    x = PencilArray.zeros(pen_x)

    def f(a):
        return transpose(a, pen_y).data

    c = count_collectives(hlo_of(f, x))
    assert c["all-to-all"] == 1, c
    assert c["all-gather"] == 0 and c["collective-permute"] == 0, c


def test_local_permutation_change_no_collectives(topo):
    """Same decomposition, different storage order: zero communication."""
    shape = (16, 16, 16)
    pen_a = Pencil(topo, shape, (1, 2))
    pen_b = pen_a.replace(permutation=Permutation(2, 1, 0))
    x = PencilArray.zeros(pen_a)

    def f(a):
        return transpose(a, pen_b).data

    c = count_collectives(hlo_of(f, x))
    assert sum(c.values()) == 0, c


def test_fft_plan_exchange_budget(topo):
    """A 3-D forward FFT is exactly N-1 = 2 transposes -> 2 all-to-alls."""
    plan = PencilFFTPlan(topo, (16, 16, 16), real=True, dtype=jnp.float32)
    x = plan.allocate_input()

    def f(a):
        return plan.forward(PencilArray(plan.input_pencil, a)).data

    c = count_collectives(hlo_of(f, x.data))
    assert c["all-to-all"] == 2, c
    assert c["all-gather"] == 0, c


def test_ns_step_collective_budget(topo):
    """One RK2 NS step = 2 nonlinear evals x (one batched 6-component
    backward chain + one forward chain) x 2 transposes = 8 all-to-alls,
    and crucially ZERO all-gathers (each would be a full-array
    replication across the pod)."""
    from pencilarrays_tpu.models import NavierStokesSpectral, taylor_green

    model = NavierStokesSpectral(topo, 16, viscosity=1e-2, dtype=jnp.float32)
    uh = taylor_green(model)

    def f(d):
        return model.step(PencilArray(uh.pencil, d, (3,)), 1e-2).data

    c = count_collectives(hlo_of(f, uh.data))
    assert c["all-gather"] == 0, c
    assert c["all-to-all"] == 8, c


def test_rk4_step_collective_budget(topo):
    """RK4: 4 nonlinear evaluations x 4 exchanges = 16 all-to-alls,
    ZERO all-gathers (the RK2 twin is test_ns_step_collective_budget)."""
    from pencilarrays_tpu.models import NavierStokesSpectral, taylor_green

    model = NavierStokesSpectral(topo, 16, viscosity=1e-2, dtype=jnp.float32)
    uh = taylor_green(model)

    def f(d):
        return model.step_rk4(PencilArray(uh.pencil, d, (3,)), 1e-2).data

    c = count_collectives(hlo_of(f, uh.data))
    assert c["all-gather"] == 0, c
    assert c["all-to-all"] == 16, c


def test_transpose_executable_cache(topo):
    """Repeated eager transposes must reuse the compiled executable — the
    framework's analog of the reference's @inferred zero-cost assertions
    (a cache miss per call cost 250x in early profiling)."""
    from pencilarrays_tpu.parallel.transpositions import _compiled_transpose

    shape = (16, 16, 16)
    pen_a = Pencil(topo, shape, (1, 2))
    pen_b = Pencil(topo, shape, (0, 2))
    x = PencilArray.zeros(pen_a)
    _compiled_transpose.cache_clear()
    transpose(x, pen_b)
    misses_after_first = _compiled_transpose.cache_info().misses
    for _ in range(5):
        transpose(x, pen_b)
    info = _compiled_transpose.cache_info()
    assert info.misses == misses_after_first  # no re-trace
    assert info.hits >= 5


def test_masked_reduction_single_all_reduce(topo):
    """Padding masking must not add communication beyond the reduce."""
    from pencilarrays_tpu import ops

    pen = Pencil(topo, (13, 11, 9), (1, 2))
    x = PencilArray.zeros(pen)

    def f(a):
        return ops.sum(PencilArray(pen, a))

    c = count_collectives(hlo_of(f, x.data))
    assert c["all-to-all"] == 0 and c["all-gather"] == 0, c
    # GSPMD may reduce per mesh axis (one all-reduce per axis is optimal
    # staged reduction, not waste)
    assert c["all-reduce"] <= 2, c


def test_collective_stats_parser(topo):
    """The cost-model parser (utils/hlo.py) agrees with the opcode counter
    on real compiled HLO, and handles async `-start` forms with TPU tiled
    layouts (nested parens) on synthetic text."""
    from pencilarrays_tpu.utils.hlo import collective_stats

    shape = (16, 16, 16)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2))
    x = PencilArray.zeros(pen_x)
    hlo = hlo_of(lambda a: transpose(a, pen_y).data, x)
    stats = collective_stats(hlo)
    assert stats["all-to-all"]["count"] == count_collectives(hlo)["all-to-all"]
    # per-shard result bytes: the exchanged tile is the full local block
    assert stats["all-to-all"]["bytes"] > 0

    synth = (
        "%ag = (f32[4,8]{1,0:T(8,128)}, f32[16,8]{1,0:T(8,128)}) "
        "all-gather-start(f32[4,8]{1,0:T(8,128)} %p), replica_groups={{0,1}}\n"
        "%agd = f32[16,8]{1,0:T(8,128)} all-gather-done((f32[4,8], "
        "f32[16,8]) %ag)\n"
        "%gte = f32[4] get-tuple-element((f32[4], f32[4]) %all-to-all.3)\n"
        "%add = f32[4]{0} add(f32[4]{0} %y, f32[4]{0} %all-to-all.9)\n"
    )
    s = collective_stats(synth)
    assert s["all-gather"]["count"] == 1  # -start counted, -done not
    assert "all-to-all" not in s  # name references don't count


@pytest.mark.slow  # interpret-mode pallas kernels compile slowly on CPU
def test_ring_pallas_fwd_bwd_collective_budget(devices):
    """The hand-kernel ring's wire budget: forward = P-1 k/v rotations;
    backward = its recompute ring (the rotating k/v AND the rotating
    dk/dv accumulator, P shifts each to complete the cycle home).  Any
    extra collective is a resharding bug."""
    from pencilarrays_tpu.models import ring_attention

    P = 4
    topo_seq = Topology((P,), devices=devices[:P])
    S, H, D = 32, 2, 16
    pen = Pencil(topo_seq, (S, H), (0,))
    q = PencilArray.zeros(pen, (D,), jnp.float32)

    def grad_fn(d):
        def loss(d_):
            o = ring_attention(PencilArray(pen, d_, (D,)),
                               PencilArray(pen, d_ + 1.0, (D,)),
                               PencilArray(pen, d_ * 2.0, (D,)),
                               causal=True, impl="pallas")
            return jnp.sum(o.data ** 2)
        return jax.grad(loss)(d)

    c = count_collectives(hlo_of(grad_fn, q.data))
    # Naively: P-1 fwd rotations + P bwd kv re-rotations + P dkv
    # rotations.  The compiled artifact is tighter: the bwd's kv
    # re-rotation chain is IDENTICAL to the fwd's, so XLA CSEs it away
    # entirely (and DCEs the last unused kv shift) — what ships is
    # (P-1) shared kv rotations + P dkv rotations = 2P-1.
    assert c["collective-permute"] == 2 * P - 1, c
    assert c["all-to-all"] == 0 and c["all-gather"] == 0, c
