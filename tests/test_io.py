"""PencilIO tests — parity with reference ``test/io.jl``: round-trips,
on-disk layout verified from raw bytes + JSON offsets, append mode,
metadata-less read, chunked layout, decomposition-independent restart."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Permutation, Topology, gather
from pencilarrays_tpu.io import (
    BinaryDriver,
    OrbaxDriver,
    has_orbax,
    metadata,
    open_file,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


@pytest.fixture
def pen(topo):
    return Pencil(topo, (11, 13, 10), (1, 2), permutation=Permutation(2, 0, 1))


def make_data(pen, extra=(), seed=0, dtype=np.float64):
    shape = pen.size_global() + extra
    u = np.random.default_rng(seed).standard_normal(shape).astype(dtype)
    return u, PencilArray.from_global(pen, u, )


def test_metadata(pen):
    _, x = make_data(pen)
    m = metadata(x)
    assert m["decomposed_dims"] == [1, 2]
    assert m["process_dims"] == [2, 4]
    assert m["permutation"] == [2, 0, 1]
    assert m["extra_dims"] == []


def test_binary_roundtrip_discontiguous(tmp_path, pen):
    u, x = make_data(pen)
    path = str(tmp_path / "data.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open_file(BinaryDriver(), path, read=True) as f:
        y = f.read("u", pen)
    np.testing.assert_array_equal(gather(y), u)


def test_on_disk_layout_is_logical_global_order(tmp_path, pen):
    """The defining property of the discontiguous layout: raw bytes at the
    JSON offset are the array in global logical order (the analog of
    re-reading serially from raw bytes, ``test/io.jl:62-103``)."""
    u, x = make_data(pen)
    path = str(tmp_path / "data.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open(path + ".json") as jf:
        meta = json.load(jf)
    d = meta["datasets"][0]
    raw = np.fromfile(path, dtype=np.float64,
                      offset=d["offset_bytes"]).reshape(d["dims_logical"])
    np.testing.assert_array_equal(raw, u)


def test_append_multiple_datasets(tmp_path, pen):
    u, x = make_data(pen, seed=1)
    v, y = make_data(pen, seed=2)
    path = str(tmp_path / "data.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open_file(BinaryDriver(), path, append=True, write=True) as f:
        f.write("v", y)
    with open_file(BinaryDriver(), path, read=True) as f:
        assert {d["name"] for d in f.datasets} == {"u", "v"}
        np.testing.assert_array_equal(gather(f.read("u", pen)), u)
        np.testing.assert_array_equal(gather(f.read("v", pen)), v)


def test_decomposition_independent_restart(tmp_path, pen, topo, devices):
    """Write under one decomposition, read under others
    (``mpi_io.jl:159-167``)."""
    u, x = make_data(pen)
    path = str(tmp_path / "data.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    # different decomp dims + permutation, same topology
    pen2 = Pencil(topo, (11, 13, 10), (0, 1), permutation=Permutation(1, 2, 0))
    # different topology shape entirely
    topo3 = Topology((8,))
    pen3 = Pencil(topo3, (11, 13, 10), (1,))
    with open_file(BinaryDriver(), path, read=True) as f:
        for p in (pen2, pen3):
            y = f.read("u", p)
            assert y.pencil == p
            np.testing.assert_array_equal(gather(y), u)


def test_chunks_layout(tmp_path, pen, topo):
    u, x = make_data(pen)
    path = str(tmp_path / "chunked.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x, chunks=True)
    with open(path + ".json") as jf:
        d = json.load(jf)["datasets"][0]
    assert d["layout"] == "chunks"
    assert len(d["chunk_map"]) == 8
    # chunk 0's bytes are the local block in memory order (mpi_io.jl:382-424)
    ch0 = d["chunk_map"][0]
    raw = np.fromfile(path, dtype=np.float64,
                      count=int(np.prod(ch0["dims_memory"])),
                      offset=ch0["offset_bytes"]).reshape(ch0["dims_memory"])
    from pencilarrays_tpu import MemoryOrder

    blk = np.asarray(x.local_block((0, 0), MemoryOrder))
    np.testing.assert_array_equal(raw, blk)
    # read back under a different configuration
    pen2 = Pencil(topo, (11, 13, 10), (0, 2))
    with open_file(BinaryDriver(), path, read=True) as f:
        y = f.read("u", pen2)
    np.testing.assert_array_equal(gather(y), u)


def test_extra_dims_io(tmp_path, topo):
    pen = Pencil(topo, (6, 8, 9), (1, 2))
    u, x = make_data(pen, extra=(3,))
    path = str(tmp_path / "vec.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("v", x)
    with open_file(BinaryDriver(), path, read=True) as f:
        y = f.read("v", pen)
    assert y.extra_dims == (3,)
    np.testing.assert_array_equal(gather(y), u)


def test_append_creates_missing_file(tmp_path, pen):
    """append on a nonexistent file creates it (Julia open-flags semantics:
    append implies create)."""
    u, x = make_data(pen)
    path = str(tmp_path / "fresh.bin")
    with open_file(BinaryDriver(), path, append=True) as f:
        f.write("u", x)
    with open_file(BinaryDriver(), path, read=True) as f:
        np.testing.assert_array_equal(gather(f.read("u", pen)), u)


def test_metadata_less_read(tmp_path, pen):
    u, x = make_data(pen)
    path = str(tmp_path / "data.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    os.remove(path + ".json")
    with open_file(BinaryDriver(), path, read=True) as f:
        y = f.read_raw(pen, np.float64, offset=0)
    np.testing.assert_array_equal(gather(y), u)


def test_read_validation(tmp_path, pen, topo):
    u, x = make_data(pen)
    path = str(tmp_path / "data.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open_file(BinaryDriver(), path, read=True) as f:
        with pytest.raises(KeyError):
            f.read("nope", pen)
        with pytest.raises(ValueError, match="dims"):
            f.read("u", Pencil(topo, (11, 13, 11), (1, 2)))
    with pytest.raises(PermissionError):
        with open_file(BinaryDriver(), path, read=True) as f:
            f.write("w", x)


def test_uniquify_names(tmp_path, pen):
    """BinaryDriver(uniquify_names=True): repeat names get suffixes
    instead of replacement (convenience beyond the reference driver)."""
    u, x = make_data(pen, seed=1)
    v, y = make_data(pen, seed=2)
    path = str(tmp_path / "uq.bin")
    drv = BinaryDriver(uniquify_names=True)
    with open_file(drv, path, write=True, create=True) as f:
        f.write("u", x)
        f.write("u", y)
    with open_file(BinaryDriver(), path, read=True) as f:
        assert {d["name"] for d in f.datasets} == {"u", "u(2)"}
        np.testing.assert_array_equal(gather(f.read("u", pen)), u)
        np.testing.assert_array_equal(gather(f.read("u(2)", pen)), v)


def test_hdf5_chunked_option(tmp_path, pen):
    from pencilarrays_tpu.io import HDF5Driver, has_hdf5

    if not has_hdf5():
        pytest.skip("h5py unavailable")
    import h5py

    u, x = make_data(pen)
    path = str(tmp_path / "ck.h5")
    with open_file(HDF5Driver(chunks=True), path, write=True,
                   create=True) as f:
        f.write("u", x)
    with h5py.File(path, "r") as h:
        assert h["u"].chunks is not None  # chunked storage
        np.testing.assert_array_equal(h["u"][...], u)
    with open_file(HDF5Driver(), path, read=True) as f:
        np.testing.assert_array_equal(gather(f.read("u", pen)), u)


def test_native_strided_io_direct(tmp_path):
    """Unit test of the C++ scatter/gather against numpy ground truth."""
    from pencilarrays_tpu.io import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    gdims = (7, 9, 5)
    full = np.zeros(gdims, dtype=np.float64)
    path = str(tmp_path / "raw.bin")
    with open(path, "wb") as f:
        f.write(full.tobytes())
    rng = np.random.default_rng(0)
    # scatter two blocks, then compare with numpy assembling
    blocks = [((1, 2, 0), rng.standard_normal((3, 4, 5))),
              ((4, 6, 1), rng.standard_normal((3, 3, 4)))]
    for start, b in blocks:
        native.scatter_write(path, 0, b, gdims, start)
        sl = tuple(slice(s, s + e) for s, e in zip(start, b.shape))
        full[sl] = b
    raw = np.fromfile(path, dtype=np.float64).reshape(gdims)
    np.testing.assert_array_equal(raw, full)
    # gather back a sub-block
    got = native.gather_read(path, 0, np.float64, gdims, (2, 3, 1), (4, 5, 3))
    np.testing.assert_array_equal(got, full[2:6, 3:8, 1:4])
    # out-of-bounds block rejected
    with pytest.raises(OSError):
        native.gather_read(path, 0, np.float64, gdims, (5, 0, 0), (4, 1, 1))


def test_native_multithreaded_and_coalesced(tmp_path):
    """The MT row-split and trailing-dim run coalescing paths produce
    bit-identical files/reads: full-extent trailing dims (coalesces to
    one region), interior strided blocks split across threads, and a
    2-D edge shape."""
    from pencilarrays_tpu.io import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(3)
    path = str(tmp_path / "mt.bin")

    cases = [
        # (gdims, start, bdims): trailing dims complete -> coalesce
        ((6, 8, 10), (2, 0, 0), (3, 8, 10)),
        # interior block, nothing coalesces
        ((16, 12, 9), (3, 2, 1), (9, 7, 5)),
        # only last dim complete
        ((10, 10, 6), (1, 2, 0), (4, 5, 6)),
        # 2-D
        ((40, 30), (8, 5), (20, 11)),
        # LARGE strided block (~12 MiB f64 > 2 * 4 MiB/thread floor) so
        # parallel_runs actually spawns threads: the r0-unravel,
        # mid-range buffer pointers and per-thread fds are exercised,
        # not silently skipped under the small-block floor
        ((48, 256, 300), (5, 3, 100), (40, 250, 150)),
    ]
    for gdims, start, bdims in cases:
        full = rng.standard_normal(gdims)
        with open(path, "wb") as f:
            f.write(full.tobytes())
        patch = rng.standard_normal(bdims)
        native.scatter_write(path, 0, patch, gdims, start, nthreads=8)
        sl = tuple(slice(s, s + e) for s, e in zip(start, bdims))
        full[sl] = patch
        raw = np.fromfile(path, dtype=np.float64).reshape(gdims)
        np.testing.assert_array_equal(raw, full)
        got = native.gather_read(path, 0, np.float64, gdims, start, bdims,
                                 nthreads=8)
        np.testing.assert_array_equal(got, patch)


def test_io_threads_env(monkeypatch):
    from pencilarrays_tpu.io import native

    monkeypatch.delenv("PENCILARRAYS_TPU_IO_THREADS", raising=False)
    assert native.default_threads() == 1  # measured verdict: see docstring
    monkeypatch.setenv("PENCILARRAYS_TPU_IO_THREADS", "6")
    assert native.default_threads() == 6
    monkeypatch.setenv("PENCILARRAYS_TPU_IO_THREADS", "99")
    assert native.default_threads() == 16


def test_roundtrip_without_native(tmp_path, pen, monkeypatch):
    """The pure-NumPy fallback path must behave identically."""
    from pencilarrays_tpu.io import native

    monkeypatch.setattr(native, "available", lambda: False)
    u, x = make_data(pen)
    path = str(tmp_path / "fallback.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open_file(BinaryDriver(), path, read=True) as f:
        y = f.read("u", pen)
    np.testing.assert_array_equal(gather(y), u)


def test_hdf5_roundtrip_and_attrs(tmp_path, pen, topo):
    """HDF5 driver parity (``test/io.jl:135-189``): round trip, attribute
    metadata, ecosystem readability, decomposition-independent restore."""
    from pencilarrays_tpu.io import HDF5Driver, has_hdf5

    if not has_hdf5():
        pytest.skip("h5py unavailable")
    import h5py

    u, x = make_data(pen, extra=(2,))
    path = str(tmp_path / "data.h5")
    with open_file(HDF5Driver(), path, write=True, create=True) as f:
        f.write("u", x)
    # plain h5py sees a logical-order dataset (ecosystem interop)
    with h5py.File(path, "r") as h:
        np.testing.assert_array_equal(h["u"][...], u)
    with open_file(HDF5Driver(), path, read=True) as f:
        assert f.datasets() == ["u"]
        attrs = f.attributes("u")
        assert attrs["decomposed_dims"] == [1, 2]
        assert attrs["permutation"] == [2, 0, 1]
        y = f.read("u", pen)
        np.testing.assert_array_equal(gather(y), u)
        # restore under a different topology
        pen3 = Pencil(Topology((8,)), (11, 13, 10), (1,))
        z = f.read("u", pen3)
        np.testing.assert_array_equal(gather(z), u)
        with pytest.raises(ValueError, match="dims"):
            f.read("u", Pencil(topo, (11, 13, 11), (1, 2)))
    # overwrite in append mode reuses the dataset in place (no HDF5 space
    # leak from del+create)
    size_before = os.path.getsize(path)
    v, xv = make_data(pen, extra=(2,), seed=9)
    with open_file(HDF5Driver(), path, append=True) as f:
        f.write("u", xv)
    with open_file(HDF5Driver(), path, read=True) as f:
        np.testing.assert_array_equal(gather(f.read("u", pen)), v)
    # allow small metadata growth but not a leaked full-dataset copy
    assert os.path.getsize(path) < size_before + u.nbytes // 2


def test_hdf5_bfloat16(tmp_path, topo):
    """bf16 (no native HDF5 type) stores as bit pattern + marker attr."""
    from pencilarrays_tpu.io import HDF5Driver, has_hdf5

    if not has_hdf5():
        pytest.skip("h5py unavailable")
    pen = Pencil(topo, (8, 8, 8), (1, 2))
    u = np.random.default_rng(0).standard_normal((8, 8, 8)).astype("bfloat16")
    x = PencilArray.from_global(pen, u)
    path = str(tmp_path / "bf16.h5")
    with open_file(HDF5Driver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open_file(HDF5Driver(), path, read=True) as f:
        y = f.read("u", pen)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_array_equal(gather(y).view(np.uint16),
                                  u.view(np.uint16))


@pytest.mark.skipif(not has_orbax(), reason="orbax not installed")
def test_orbax_async_write(tmp_path, pen, topo):
    """Async checkpointing: write returns early, close() makes durable,
    read after close is exact."""
    u, x = make_data(pen, seed=11)
    path = str(tmp_path / "actx")
    with open_file(OrbaxDriver(async_write=True), path, write=True,
                   create=True) as f:
        f.write("u", x)  # returns before serialization completes
    with open_file(OrbaxDriver(), path, read=True) as f:
        np.testing.assert_array_equal(gather(f.read("u", pen)), u)


@pytest.mark.skipif(not has_orbax(), reason="orbax not installed")
def test_orbax_roundtrip(tmp_path, pen, topo):
    u, x = make_data(pen)
    path = str(tmp_path / "ckpt")
    with open_file(OrbaxDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open_file(OrbaxDriver(), path, read=True) as f:
        assert f.datasets() == ["u"]
        y = f.read("u", pen)
        np.testing.assert_array_equal(gather(y), u)
        # decomposition-independent restore
        pen2 = Pencil(topo, (11, 13, 10), (0, 1))
        z = f.read("u", pen2)
        np.testing.assert_array_equal(gather(z), u)

def test_rewrite_reuses_offset(tmp_path, pen):
    """Rewriting a same-size dataset ping-pongs between two regions
    (ADVICE r1+r2: bounded file growth under checkpoint rotation AND
    crash safety — the sidecar's current region is never overwritten);
    other datasets survive the rewrite."""
    u, x = make_data(pen, seed=1)
    v, y = make_data(pen, seed=2)
    w, z = make_data(pen, seed=3)
    path = str(tmp_path / "rw.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
        f.write("v", y)
    with open_file(BinaryDriver(), path, append=True, write=True) as f:
        f.write("u", y)  # first rewrite allocates the spare region
    size1 = os.path.getsize(path)
    for arr in (z, x, y, z):  # further rewrites reuse the two regions
        with open_file(BinaryDriver(), path, append=True, write=True) as f:
            f.write("u", arr)
    assert os.path.getsize(path) == size1
    with open_file(BinaryDriver(), path, read=True) as f:
        np.testing.assert_array_equal(gather(f.read("u", pen)), w)
        np.testing.assert_array_equal(gather(f.read("v", pen)), v)


def test_rewrite_crash_leaves_old_checkpoint_intact(tmp_path, pen):
    """Crash-consistency of the ping-pong rewrite: bytes referenced by
    the PRE-rewrite sidecar are untouched by the rewrite, so a crash
    before the sidecar flush (simulated by restoring the old sidecar)
    still reads the previous checkpoint."""
    import shutil

    u, x = make_data(pen, seed=6)
    w, z = make_data(pen, seed=7)
    path = str(tmp_path / "crash.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    shutil.copy(path + ".json", path + ".json.bak")  # pre-crash sidecar
    with open_file(BinaryDriver(), path, append=True, write=True) as f:
        f.write("u", z)  # rewrite fully lands (data + new sidecar)
    shutil.copy(path + ".json.bak", path + ".json")  # "crash" rollback
    with open_file(BinaryDriver(), path, read=True) as f:
        np.testing.assert_array_equal(gather(f.read("u", pen)), u)


def test_reuse_regions_opt_out(tmp_path, pen):
    """reuse_regions=False restores append-only rewrites (crash-safe
    rotation: old bytes survive until the sidecar re-flush)."""
    u, x = make_data(pen, seed=4)
    w, z = make_data(pen, seed=5)
    path = str(tmp_path / "ao.bin")
    drv = BinaryDriver(reuse_regions=False)
    with open_file(drv, path, write=True, create=True) as f:
        f.write("u", x)
    size0 = os.path.getsize(path)
    with open_file(drv, path, append=True, write=True) as f:
        f.write("u", z)
    assert os.path.getsize(path) == 2 * size0  # appended, not reused
    with open_file(BinaryDriver(), path, read=True) as f:
        np.testing.assert_array_equal(gather(f.read("u", pen)), w)


def test_collection_io_binary(tmp_path, topo, pen):
    """A (u, v, w, p) state writes as ONE dataset and restarts — under a
    DIFFERENT decomposition — in one call (collection-level I/O,
    reference ext/PencilArraysHDF5Ext.jl:222-229)."""
    fields = [make_data(pen, seed=20 + i) for i in range(4)]
    path = str(tmp_path / "coll.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("state", tuple(x for _, x in fields))
    pen2 = Pencil(topo, pen.size_global(), (0, 1))
    with open_file(BinaryDriver(), path, read=True) as f:
        back = f.read("state", pen2)
    assert isinstance(back, tuple) and len(back) == 4
    for (u, _), b in zip(fields, back):
        assert b.extra_dims == ()
        np.testing.assert_array_equal(gather(b), u)


def test_collection_io_binary_chunks_and_extra_dims(tmp_path, pen):
    """Collections of fields that THEMSELVES have extra dims, through
    the chunked layout."""
    fields = [make_data(pen, extra=(2,), seed=30 + i) for i in range(3)]
    path = str(tmp_path / "collc.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("state", [x for _, x in fields], chunks=True)
    with open_file(BinaryDriver(), path, read=True) as f:
        back = f.read("state", pen)
    assert isinstance(back, tuple) and len(back) == 3
    for (u, _), b in zip(fields, back):
        assert b.extra_dims == (2,)
        np.testing.assert_array_equal(gather(b), u)


def test_collection_io_hdf5(tmp_path, topo, pen):
    pytest.importorskip("h5py")
    from pencilarrays_tpu.io import HDF5Driver

    fields = [make_data(pen, seed=40 + i) for i in range(4)]
    path = str(tmp_path / "coll.h5")
    with open_file(HDF5Driver(), path, write=True, create=True) as f:
        f.write("state", tuple(x for _, x in fields))
    pen2 = Pencil(topo, pen.size_global(), (0, 2))
    with open_file(HDF5Driver(), path, read=True) as f:
        back = f.read("state", pen2)
    assert isinstance(back, tuple) and len(back) == 4
    for (u, _), b in zip(fields, back):
        np.testing.assert_array_equal(gather(b), u)
    # single-array rewrite under the same name clears the marker
    with open_file(HDF5Driver(), path, append=True, write=True) as f:
        f.write("state", fields[0][1])
    with open_file(HDF5Driver(), path, read=True) as f:
        one = f.read("state", pen)
    assert not isinstance(one, tuple)


def test_collection_io_orbax(tmp_path, topo, pen):
    if not has_orbax():
        pytest.skip("orbax not available")
    fields = [make_data(pen, seed=50 + i) for i in range(3)]
    path = str(tmp_path / "coll_orbax")
    with open_file(OrbaxDriver(), path, write=True, create=True) as f:
        f.write("state", tuple(x for _, x in fields))
    pen2 = Pencil(topo, pen.size_global(), (0, 1))
    with open_file(OrbaxDriver(), path, read=True) as f:
        back = f.read("state", pen2)
    assert isinstance(back, tuple) and len(back) == 3
    for (u, _), b in zip(fields, back):
        np.testing.assert_array_equal(gather(b), u)


def test_collection_write_streams_host_side(pen):
    """Collection writes go through a CollectionView whose blocks are
    HOST-stacked per shard — no stacked duplicate of the state ever
    exists in device memory (round-3 review finding)."""
    from pencilarrays_tpu.io.binary import iter_local_blocks
    from pencilarrays_tpu.io.core import CollectionView, pack_collection

    fields = [make_data(pen, seed=60 + i)[1] for i in range(3)]
    view, n = pack_collection(tuple(fields))
    assert isinstance(view, CollectionView) and n == 3
    assert view.extra_dims == (3,)
    blocks = list(iter_local_blocks(view))
    assert blocks, "no local blocks"
    for start, b in blocks:
        assert isinstance(b, np.ndarray)  # host memory, not jax.Array
        assert b.shape[-1] == 3
        assert start[-1] == 0


def test_orbax_legacy_stacked_collection_readable(tmp_path, topo, pen):
    """Pre-round-3 orbax collection checkpoints stored ONE stacked array
    under 'data'; the reader detects that layout (padded shape carries
    the trailing component dim) and still restores the tuple."""
    if not has_orbax():
        pytest.skip("orbax not available")
    import json as _json

    fields = [make_data(pen, seed=70 + i) for i in range(2)]
    stacked = PencilArray.stack([x for _, x in fields])
    path = str(tmp_path / "legacy_orbax")
    with open_file(OrbaxDriver(), path, write=True, create=True) as f:
        f.write("state", stacked)  # plain stacked write, 'data' item
    # forge the legacy metadata: mark it a collection
    mp = os.path.join(path, "state.meta.json")
    with open(mp) as fh:
        meta = _json.load(fh)
    meta["metadata"]["collection"] = 2
    with open(mp, "w") as fh:
        _json.dump(meta, fh)
    with open_file(OrbaxDriver(), path, read=True) as f:
        back = f.read("state", pen)
    assert isinstance(back, tuple) and len(back) == 2
    for (u, _), b in zip(fields, back):
        np.testing.assert_array_equal(gather(b), u)
