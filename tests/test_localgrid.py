"""Grid tests — parity with reference ``test/localgrid.jl`` semantics: the
fused grid broadcast must reproduce elementwise f(x,y,z) exactly."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from pencilarrays_tpu import (
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    gather,
    localgrid,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


@pytest.mark.parametrize("perm", [None, Permutation(2, 0, 1)])
def test_grid_broadcast_matches_numpy(topo, perm):
    # the README/grids.jl benchmark expression
    shape = (13, 11, 10)
    pen = Pencil(topo, shape, (1, 2), permutation=perm)
    xs = np.linspace(0.0, 1.0, shape[0])
    ys = np.linspace(0.0, 2.0, shape[1])
    zs = np.linspace(0.0, 3.0, shape[2])
    g = localgrid(pen, (xs, ys, zs))
    u = g.evaluate(lambda x, y, z: x + 2 * y * jnp.cos(z))
    expect = xs[:, None, None] + 2 * ys[None, :, None] * np.cos(zs[None, None, :])
    np.testing.assert_allclose(gather(u), expect, rtol=1e-6)


def test_components_and_names(topo):
    shape = (8, 10, 12)
    pen = Pencil(topo, shape, (1, 2))
    g = localgrid(pen, [np.arange(n, dtype=float) for n in shape])
    assert g.ndims == 3
    # named access g.x/g.y/g.z (rectilinear.jl:159-169)
    assert g.x.shape == (8, 1, 1)
    assert g.y.shape == (1, 10, 1)  # 10 divides evenly over 2 -> unpadded
    assert g.z.shape == (1, 1, 12)
    with pytest.raises(AttributeError):
        g.w
    assert len(g.components()) == 3
    np.testing.assert_array_equal(np.asarray(g.coordinate(0)), np.arange(8.0))


def test_grid_with_permutation_positions(topo):
    shape = (8, 10, 12)
    perm = Permutation(2, 0, 1)
    pen = Pencil(topo, shape, (1, 2), permutation=perm)
    g = localgrid(pen, [np.arange(n, dtype=float) for n in shape])
    # memory order is (dim2, dim0, dim1): components' non-singleton axis
    # must sit at the memory position
    assert g.x.shape[1] == 8
    assert g.y.shape[2] >= 10
    assert g.z.shape[0] == 12


def test_grid_broadcast_with_array(topo):
    shape = (13, 11, 10)
    pen = Pencil(topo, shape, (1, 2), permutation=Permutation(1, 2, 0))
    u_np = np.random.default_rng(0).standard_normal(shape)
    u = PencilArray.from_global(pen, u_np)
    g = localgrid(pen, [np.linspace(0, 1, n) for n in shape])

    # v = u * x + z, fused in memory order through .map + components
    @jax.jit
    def f(a):
        return a.map(lambda d: d * g[0] + g[2])

    v = f(u)
    xs, _, zs = [np.linspace(0, 1, n) for n in shape]
    expect = u_np * xs[:, None, None] + zs[None, None, :]
    np.testing.assert_allclose(gather(v), expect, rtol=1e-6)


def test_evaluate_extra_dims(topo):
    shape = (8, 10, 12)
    pen = Pencil(topo, shape, (1, 2))
    g = localgrid(pen, [np.arange(n, dtype=float) for n in shape])
    u = g.evaluate(lambda x, y, z: x + y + z, extra_dims=(3,))
    assert u.extra_dims == (3,)
    expect = (np.arange(8.0)[:, None, None] + np.arange(10.0)[None, :, None]
              + np.arange(12.0)[None, None, :])
    got = gather(u)
    for c in range(3):
        np.testing.assert_allclose(got[..., c], expect)


def test_grid_iteration_and_meshgrid(topo):
    """Iteration yields coordinate tuples in memory order (reference
    ``rectilinear.jl:110-130``); meshgrid gives dense coordinate fields."""
    shape = (3, 4, 2)
    pen = Pencil(topo, shape, (1, 2), permutation=Permutation(2, 0, 1))
    xs = [np.arange(n, dtype=float) * (d + 1) for d, n in enumerate(shape)]
    g = localgrid(pen, xs)
    pts = list(g)
    assert len(pts) == len(g) == 24
    # every grid point exactly once, each a logical coordinate tuple
    expect = {(xs[0][i], xs[1][j], xs[2][k])
              for i in range(3) for j in range(4) for k in range(2)}
    assert set(pts) == expect
    # memory order (2,0,1): dim 1 is last in memory -> fastest
    assert pts[0][1] == 0.0 and pts[1][1] == 2.0
    # meshgrid fields agree with evaluate of identity components
    mx, my, mz = g.meshgrid()
    got = gather(PencilArray(pen, mx))
    np.testing.assert_array_equal(got, np.broadcast_to(
        xs[0][:, None, None], shape))


def test_validation(topo):
    pen = Pencil(topo, (8, 10, 12), (1, 2))
    with pytest.raises(ValueError):
        localgrid(pen, [np.arange(8.0), np.arange(10.0)])
    with pytest.raises(ValueError):
        localgrid(pen, [np.arange(8.0), np.arange(10.0), np.arange(13.0)])


def test_zip_with(topo):
    """zip(eachindex(u), grid) analog (benchmarks/grids.jl:117): values
    and coordinates fuse into one elementwise kernel."""
    import jax.numpy as jnp

    pen = Pencil(topo, (13, 11, 10), (1, 2), permutation=Permutation(2, 0, 1))
    coords = [np.linspace(0, 1, n) for n in pen.size_global()]
    g = localgrid(pen, coords)
    u = np.random.default_rng(5).standard_normal(pen.size_global())
    x = PencilArray.from_global(pen, u)
    v = g.zip_with(lambda a, gx, gy, gz: a + gx + 2.0 * gy * jnp.cos(gz), x)
    assert isinstance(v, PencilArray)
    X, Y, Z = np.meshgrid(*coords, indexing="ij")
    np.testing.assert_allclose(gather(v), u + X + 2.0 * Y * np.cos(Z),
                               rtol=1e-12)
    with pytest.raises(ValueError, match="pencil"):
        g.zip_with(lambda a, *k: a,
                   PencilArray.zeros(pen.replace(decomp_dims=(0, 2))))
