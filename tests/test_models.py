"""Flagship model tests: spectral Navier-Stokes (distributed correctness =
decomposition independence; physics sanity = divergence-free, viscous
decay) and the adaptive ODE integrator (global-norm dt control + global
NaN detection, ``test/ode.jl`` parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Topology, gather
from pencilarrays_tpu import ops
from pencilarrays_tpu.models import (
    DiffusionSpectral,
    NavierStokesSpectral,
    integrate,
    taylor_green,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def test_taylor_green_init(topo):
    model = NavierStokesSpectral(topo, 16, viscosity=0.01, dtype=jnp.float64)
    uh = taylor_green(model)
    assert uh.extra_dims == (3,)
    # Taylor-Green kinetic energy: <|u|^2>/2 = 1/8
    e0 = float(model.energy(uh))
    assert e0 == pytest.approx(0.125, rel=1e-6)
    # divergence-free in spectral space: k . u = 0 (PencilArray-level
    # broadcasting: logical-order wavenumbers against components)
    (kx, ky, kz), _, _, _ = model._spectral_operators()
    div = (uh.component(0) * kx + uh.component(1) * ky
           + uh.component(2) * kz)
    from pencilarrays_tpu.ops import reductions
    assert float(reductions.maximum(abs(div))) < 1e-10


def test_step_physics(topo):
    model = NavierStokesSpectral(topo, 16, viscosity=0.05, dtype=jnp.float64)
    uh = taylor_green(model)
    e0 = float(model.energy(uh))
    step = jax.jit(lambda s: model.step(s, 0.01))
    for _ in range(5):
        uh = step(uh)
    e1 = float(model.energy(uh))
    assert e1 < e0  # viscous decay
    assert np.isfinite(e1)
    # still (near) divergence-free after stepping
    (kx, ky, kz), _, _, _ = model._spectral_operators()
    div = (uh.component(0) * kx + uh.component(1) * ky
           + uh.component(2) * kz)
    from pencilarrays_tpu.ops import reductions
    assert float(reductions.maximum(abs(div))) < 1e-8


@pytest.mark.slow  # ~20 s: full NS step on two meshes
def test_decomposition_independence(topo, devices):
    """The strongest distributed-correctness check: the same physics on a
    1-device vs 8-device mesh must agree."""
    n = 16
    r1 = NavierStokesSpectral(Topology((1,), devices=devices[:1]), n,
                              viscosity=0.02, dtype=jnp.float64)
    r8 = NavierStokesSpectral(topo, n, viscosity=0.02, dtype=jnp.float64)
    uh1, uh8 = taylor_green(r1), taylor_green(r8)
    for _ in range(3):
        uh1 = r1.step(uh1, 0.02)
        uh8 = r8.step(uh8, 0.02)
    u1 = gather(r1.to_physical(uh1))
    u8 = gather(r8.to_physical(uh8))
    np.testing.assert_allclose(u8, u1, rtol=1e-9, atol=1e-11)


@pytest.mark.slow  # ~25 s: multi-step scan rollout
def test_simulate_scan(topo):
    """Whole-trajectory lax.scan: must agree with the step-by-step loop
    and record monotone-decaying energies."""
    model = NavierStokesSpectral(topo, 16, viscosity=0.05, dtype=jnp.float64)
    uh0 = taylor_green(model)
    final, energies = jax.jit(
        lambda s: model.simulate(s, 0.01, 5, record_energy=True))(uh0)
    # equivalent to explicit stepping
    uh = uh0
    for _ in range(5):
        uh = model.step(uh, 0.01)
    # scan-compiled vs per-step-compiled programs fuse differently; allow
    # rounding-level drift (absolute, for near-zero spectral coefficients)
    np.testing.assert_allclose(np.asarray(final.data), np.asarray(uh.data),
                               rtol=1e-9, atol=1e-13)
    e = np.asarray(energies)
    assert e.shape == (5,)
    assert (np.diff(e) < 0).all()  # viscous decay


def test_diffusion_exact_solution(topo):
    """The heat equation has a closed form per mode: the whole distributed
    stack must reproduce it to FFT precision."""
    n = 16
    model = DiffusionSpectral(topo, n, kappa=0.1, dtype=jnp.float64)
    # u0 = sin(2x)cos(3y)sin(z): single separable mode, exact decay
    coords = [np.arange(n) * (2 * np.pi / n)] * 3
    from pencilarrays_tpu import localgrid

    g = localgrid(model.plan.input_pencil, coords)
    u0 = g.evaluate(
        lambda x, y, z: jnp.sin(2 * x) * jnp.cos(3 * y) * jnp.sin(z))
    t = 0.37
    got = gather(model.solve(u0, t))
    lam = 0.1 * (2**2 + 3**2 + 1**2)
    expect = gather(u0) * np.exp(-lam * t)
    np.testing.assert_allclose(got, expect, rtol=1e-10, atol=1e-12)
    # repeated stepping composes exactly like one big step
    uh = model.from_physical(u0)
    for _ in range(4):
        uh = model.step(uh, t / 4)
    np.testing.assert_allclose(gather(model.to_physical(uh)), expect,
                               rtol=1e-10, atol=1e-12)


def test_diffusion_decomposition_independence(topo, devices):
    n = 12
    u0_np = np.random.default_rng(5).standard_normal((n, n, n))
    outs = []
    for tp in (Topology((1,), devices=devices[:1]), topo):
        m = DiffusionSpectral(tp, n, kappa=0.05, dtype=jnp.float64)
        u0 = PencilArray.from_global(m.plan.input_pencil, u0_np)
        outs.append(gather(m.solve(u0, 0.2)))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-12, atol=1e-14)


def test_ode_exponential_decay(topo):
    shape = (9, 11, 13)  # ragged: padding-masked norms matter
    pen = Pencil(topo, shape, (1, 2))
    u0_np = np.random.default_rng(0).standard_normal(shape)
    u0 = PencilArray.from_global(pen, u0_np)
    lam = 1.7

    def f(t, u):
        return u.map(lambda d: -lam * d)

    u, stats = integrate(f, u0, (0.0, 1.0), rtol=1e-7, atol=1e-9)
    assert float(stats["t"]) == pytest.approx(1.0)
    assert not bool(stats["nan_detected"])
    assert int(stats["n_accepted"]) > 0
    np.testing.assert_allclose(gather(u), u0_np * np.exp(-lam), rtol=1e-5)


def test_ode_nan_detection(topo):
    shape = (8, 8, 8)
    pen = Pencil(topo, shape, (1, 2))
    u0 = PencilArray.from_global(pen, np.ones(shape))

    def f(t, u):
        # blows up: du/dt = u^3 starting at 1 diverges in finite time
        return u.map(lambda d: d * d * d * 10.0)

    u, stats = integrate(f, u0, (0.0, 10.0), rtol=1e-6, max_steps=2000)
    # blow-up MUST be reported: divergence defeats any step size, which
    # the controller detects as dt underflow (test/ode.jl:41-57 parity)
    assert bool(stats["nan_detected"])
    assert float(stats["t"]) < 10.0


def test_ode_stiff_rejection_recovers(topo):
    """An overflowing trial step must be rejected with dt shrink, not
    flagged as blow-up (regression: NaN enorm previously grew dt 5x and
    aborted)."""
    shape = (8, 8, 8)
    pen = Pencil(topo, shape, (1, 2))
    u0 = PencilArray.from_global(pen, np.ones(shape))
    lam = 1e8  # stiff decay: huge dt0 overflows the trial step

    def f(t, u):
        return u.map(lambda d: -lam * d)

    u, stats = integrate(f, u0, (0.0, 1e-7), dt0=1.0, rtol=1e-4,
                         max_steps=2000)
    assert not bool(stats["nan_detected"])
    assert float(stats["t"]) == pytest.approx(1e-7)
    np.testing.assert_allclose(gather(u), np.exp(-lam * 1e-7), rtol=1e-2)


def test_ode_under_jit(topo):
    shape = (8, 8, 8)
    pen = Pencil(topo, shape, (1, 2))
    u0 = PencilArray.from_global(pen, np.full(shape, 2.0))

    @jax.jit
    def run(u):
        return integrate(lambda t, a: a.map(lambda d: -d), u, (0.0, 0.5))

    u, stats = run(u0)
    np.testing.assert_allclose(gather(u), np.full(shape, 2.0 * np.exp(-0.5)),
                               rtol=1e-4)


def test_rk4_order(topo):
    """step_rk4 converges at 4th order (error ratio ~16 when dt halves)
    where RK2 shows ~4; both against a fine-step RK4 reference."""
    model = NavierStokesSpectral(topo, 16, viscosity=0.02, dtype=jnp.float64)
    uh0 = taylor_green(model)
    # seed a second mode so the nonlinear term is active
    uh0 = model.step(uh0, 0.02)
    T = 0.32
    # one jitted stepper each (dt traced): the whole sweep compiles twice
    j2 = jax.jit(model.step)
    j4 = jax.jit(model.step_rk4)

    def run(stepper, dt):
        u = uh0
        for _ in range(int(round(T / dt))):
            u = stepper(u, dt)
        return np.asarray(gather(u))

    ref = run(j4, T / 64)
    err4_a = np.abs(run(j4, T / 4) - ref).max()
    err4_b = np.abs(run(j4, T / 8) - ref).max()
    err2_a = np.abs(run(j2, T / 4) - ref).max()
    err2_b = np.abs(run(j2, T / 8) - ref).max()
    assert err4_a / err4_b > 9.0, (err4_a, err4_b)   # nominal 16
    assert 2.5 < err2_a / err2_b < 7.0, (err2_a, err2_b)  # nominal 4
    assert err4_b < err2_b  # RK4 strictly more accurate

