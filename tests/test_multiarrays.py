"""ManyPencilArray tests — the re-specified shared-storage transpose chain
(reference ``src/multiarrays.jl`` + in-place transposes,
``test/pencils.jl:224-239``)."""

import numpy as np
import jax.numpy as jnp
import pytest

from pencilarrays_tpu import (
    ManyPencilArray,
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    gather,
)
from pencilarrays_tpu import ops


@pytest.fixture
def pencils(devices):
    topo = Topology((2, 4))
    shape = (14, 21, 19)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2), permutation=Permutation(1, 0, 2))
    pen_z = Pencil(topo, shape, (0, 1), permutation=Permutation(2, 1, 0))
    return pen_x, pen_y, pen_z


def test_construction_and_access(pencils):
    A = ManyPencilArray(*pencils, dtype=jnp.float64)
    assert len(A) == 3
    assert A.index == 0
    assert A.first.pencil == pencils[0]
    with pytest.raises(RuntimeError, match="not live"):
        A[1]
    with pytest.raises(RuntimeError):
        _ = A.last


def test_chain_roundtrip_bit_identity(pencils):
    pen_x, pen_y, pen_z = pencils
    shape = pen_x.size_global()
    u = np.random.default_rng(7).standard_normal(shape)
    A = ManyPencilArray(pen_x, pen_y, pen_z, dtype=jnp.float64)
    A.set(PencilArray.from_global(pen_x, u))
    orig = A.current.data
    A.transpose_to(1)
    assert A.index == 1 and A.current.pencil == pen_y
    np.testing.assert_array_equal(gather(A.current), u)
    A.transpose_to(2)
    np.testing.assert_array_equal(gather(A.current), u)
    # back down the chain
    A.transpose_to(1)
    A.transpose_to(0)
    assert bool((A.current.data == orig).all())


def test_cycle_generator(pencils):
    shape = pencils[0].size_global()
    u = np.random.default_rng(8).standard_normal(shape)
    A = ManyPencilArray(*pencils, dtype=jnp.float64)
    A.set(PencilArray.from_global(pencils[0], u))
    seen = []
    for arr in A.cycle():
        seen.append(arr.pencil.decomposition)
        np.testing.assert_array_equal(gather(arr), u)
    assert seen == [(1, 2), (0, 2), (0, 1)]
    # a second sweep (the next "timestep") must chain back through the
    # intermediate configuration transparently
    for arr in A.cycle():
        np.testing.assert_array_equal(gather(arr), u)


def test_donation_invalidates_source(pencils):
    """After a donating hop the old buffer must not be reachable through
    the chain (stale views are structurally invalid)."""
    A = ManyPencilArray(*pencils, dtype=jnp.float32)
    a0 = A.current
    A.transpose_to(1)  # donate=True default
    with pytest.raises(RuntimeError):
        A[0]
    # The donated buffer is deleted on backends that honour donation (TPU);
    # the CPU test backend ignores donation, so only the structural guard
    # above is asserted unconditionally.
    assert isinstance(a0.data.is_deleted(), bool)


def test_validation(pencils, devices):
    pen_x, pen_y, _ = pencils
    with pytest.raises(ValueError):
        ManyPencilArray()
    other_topo = Topology((4, 2))
    with pytest.raises(ValueError, match="topology"):
        ManyPencilArray(pen_x, Pencil(other_topo, pen_x.size_global(), (0, 2)))
    with pytest.raises(ValueError, match="global shape"):
        ManyPencilArray(pen_x, Pencil(pen_x.topology, (8, 8, 8), (0, 2)))
    with pytest.raises(ValueError, match="not part"):
        A = ManyPencilArray(pen_x, pen_y)
        A.set(PencilArray.zeros(Pencil(pen_x.topology, pen_x.size_global(), (2, 1))))
