"""True multi-process integration test — the ``mpiexec -n 2`` analog
(reference ``test/runtests.jl:48-53``): two OS processes, each with 4
virtual devices, joined by ``jax.distributed``; the framework must behave
identically to the single-process 8-device mesh."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_integration(tmp_path):
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multiprocess_worker.py")
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # drop the TPU-claiming sitecustomize: worker processes must not race
    # for the single chip
    env["PYTHONPATH"] = os.path.dirname(here)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, "2", str(pid),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multiprocess workers timed out:\n" + "\n".join(outs))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        assert "WORKER_OK" in out, out[-2000:]
    # both processes computed the same global sum
    sums = {line.split("sum=")[1] for out in outs
            for line in out.splitlines() if "WORKER_OK" in line}
    assert len(sums) == 1
