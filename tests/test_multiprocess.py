"""True multi-process integration test — the ``mpiexec -n N`` analog
(reference ``test/runtests.jl:48-53``, which clamps to 4-6 processes):
N OS processes splitting 8 virtual devices, joined by ``jax.distributed``;
the framework must behave identically to the single-process 8-device
mesh, including the sequence-parallel attention collectives crossing the
process boundary."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nprocs", [2, 4])
def test_multi_process_integration(tmp_path, nprocs):
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multiprocess_worker.py")
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # drop the TPU-claiming sitecustomize: worker processes must not race
    # for the single chip
    env["PYTHONPATH"] = os.path.dirname(here)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, str(nprocs), str(pid),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multiprocess workers timed out:\n" + "\n".join(outs))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        assert "WORKER_OK" in out, out[-2000:]
    # both processes computed the same global sum
    sums = {line.split("sum=")[1] for out in outs
            for line in out.splitlines() if "WORKER_OK" in line}
    assert len(sums) == 1
