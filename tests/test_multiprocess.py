"""True multi-process integration test — the ``mpiexec -n N`` analog
(reference ``test/runtests.jl:48-53``, which clamps to 4-6 processes):
N OS processes splitting 8 virtual devices, joined by ``jax.distributed``;
the framework must behave identically to the single-process 8-device
mesh, including the sequence-parallel attention collectives crossing the
process boundary."""

import os
import re
import signal
import socket
import subprocess
import sys

import pytest


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_workers(worker, nprocs, extra_args, sentinel, label,
                    expect_signal=None):
    """Spawn ``nprocs`` copies of ``worker``, wait, and assert every one
    exits 0 and prints ``sentinel`` — or, with ``expect_signal``, that
    every one died from exactly that signal (the fault-injection kill
    phases).  Returns the outputs.  On timeout the already-captured
    pipes are DRAINED after the kill so the failure message carries
    everything the workers printed before hanging."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # drop the TPU-claiming sitecustomize: worker processes must not race
    # for the single chip
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(worker)))
    coordinator = (f"127.0.0.1:{_free_port()}" if nprocs > 1 else "-")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, str(nprocs), str(pid),
             *extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            # generous: N processes share ONE core on this image, and
            # unrelated background load (e.g. the round-5 TPU-capture
            # probe loop) can halve the effective core for minutes —
            # 240 s proved flaky under that contention
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # drain what the (now dead) workers managed to print — the
        # evidence trail for diagnosing the hang.  Workers that already
        # completed keep their captured output (communicate() must not
        # be re-called on them: a second call fails and would replace
        # the evidence with an empty string)
        drained = list(outs)
        for p in procs[len(outs):]:
            try:
                out, _ = p.communicate(timeout=10)
            except Exception:
                out = ""
            drained.append(out or "")
        pytest.fail(f"{label} workers timed out; captured output:\n"
                    + "\n---\n".join(drained))
    if expect_signal is not None:
        for p, out in zip(procs, outs):
            assert p.returncode == -expect_signal, (
                f"{label} worker expected signal {expect_signal}, got "
                f"returncode {p.returncode}:\n{out[-3000:]}")
        return outs
    for p, out in zip(procs, outs):
        if (p.returncode != 0 and
                "aren't implemented on the CPU backend" in out):
            # older jaxlib: the CPU backend has no cross-process
            # collective transport (gloo came later) — an environment
            # capability gap, not a framework regression
            pytest.skip("this jaxlib's CPU backend lacks multiprocess "
                        "collectives")
        assert p.returncode == 0, f"{label} worker failed:\n{out[-3000:]}"
        assert sentinel in out, out[-2000:]
    return outs


@pytest.mark.parametrize(
    "nprocs",
    [pytest.param(2, marks=pytest.mark.slow),
     pytest.param(4, marks=pytest.mark.slow)])  # ~2 / ~3 min each;
# default cross-process coverage rides test_restart_across_process_counts
def test_multi_process_integration(tmp_path, nprocs):
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "multiprocess_worker.py")
    outs = _launch_workers(worker, nprocs, [str(tmp_path)], "WORKER_OK",
                           f"multiprocess[{nprocs}]")
    # both processes computed the same global sum
    sums = {line.split("sum=")[1] for out in outs
            for line in out.splitlines() if "WORKER_OK" in line}
    assert len(sums) == 1


def _run_phase(worker, tmp_path, nprocs, phase):
    _launch_workers(worker, nprocs, [str(tmp_path), phase],
                    f"RESTART_OK phase={phase}", f"restart {phase}")


def test_restart_across_process_counts(tmp_path):
    """Write with 4 processes, restart with 2 and with 1 — different
    decomposition AND different process count each time, for both the
    binary driver and the HDF5 virtual-dataset layout (the reference's
    decomposition-independent restart promise, mpi_io.jl:159-167,
    extended across process counts)."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "restart_worker.py")
    _run_phase(worker, tmp_path, 4, "write")
    _run_phase(worker, tmp_path, 2, "read2")
    _run_phase(worker, tmp_path, 1, "read1")


def _run_kill_sequence(tmp_path, nprocs_ckpt, nprocs_kill, nprocs_recover):
    """commit step 1 -> SIGKILL mid-step-2-write -> restart: the torn
    attempt is invisible, ``latest_valid()`` lands on step 1, and the
    recovered array is bit-identical to ground truth.  The obs flight
    recorder (armed by the worker) must leave a schema-clean timeline
    that tells the whole story — including from inside the dead
    processes."""
    import signal

    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "restart_worker.py")
    _run_phase(worker, tmp_path, nprocs_ckpt, "ckpt")
    _launch_workers(worker, nprocs_kill, [str(tmp_path), "killwrite"],
                    None, "restart killwrite",
                    expect_signal=signal.SIGKILL)
    # the wreckage the crash leaves: an uncommitted temp dir only
    ckdir = os.path.join(str(tmp_path), "ckpts")
    leftovers = sorted(os.listdir(ckdir))
    assert "step-00000001" in leftovers
    assert "step-00000002" not in leftovers, leftovers
    _assert_kill_timeline(os.path.join(str(tmp_path), "obs"), after_kill=True)
    _run_phase(worker, tmp_path, nprocs_recover, "recover")
    _assert_kill_timeline(os.path.join(str(tmp_path), "obs"),
                          after_kill=False,
                          guard_recover=(nprocs_recover == 1))


def _pa_obs_check(obs_dir):
    """Run the REAL post-mortem CLI (`pa-obs lint` + `pa-obs timeline`)
    over a drill's artifacts — the drills' timeline assertions ride the
    same code path an operator types — and return the merged events."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from pencilarrays_tpu.obs.__main__ import main
    from pencilarrays_tpu.obs.timeline import merge_journals

    assert main(["lint", obs_dir]) == 0, "pa-obs lint failed"
    assert main(["timeline", obs_dir]) == 0, "pa-obs timeline failed"
    return merge_journals(obs_dir).events


def _assert_kill_timeline(obs_dir, after_kill, guard_recover=False):
    """The journal is the post-mortem: step 1 committed, step 2 began
    and hit the injected torn fault, step 2 NEVER committed — and after
    recovery, step 1 was restored.  The single-process recover variant
    additionally ran the guard's detect-and-recover ladder, so its
    timeline must carry the guard.sdc detections and a guard.recover
    sequence ending in ``recovered``.  Every record passes the schema
    lint, via the real ``pa-obs`` CLI path."""
    events = _pa_obs_check(obs_dir)
    commits = {e["step"] for e in events if e["ev"] == "ckpt.commit"}
    assert commits == {1}, commits  # step 2's commit must never exist
    begins = {e["step"] for e in events
              if e["ev"] == "ckpt.save" and e["status"] == "begin"}
    assert begins == {1, 2}, begins
    done = {e["step"] for e in events
            if e["ev"] == "ckpt.save" and e["status"] == "committed"}
    assert done == {1}, done
    # the dying processes journaled the torn firing before SIGKILL; the
    # guarded recover drill adds its own (deliberate) corrupt firings
    torn = [e for e in events if e["ev"] == "fault" and e["mode"] == "torn"]
    assert torn and all(e["point"] == "io.write_block" for e in torn), torn
    other = [e for e in events
             if e["ev"] == "fault" and e["mode"] != "torn"]
    assert all(e["point"] == "hop.exchange" and e["mode"] == "corrupt"
               for e in other), other
    restores = [e for e in events if e["ev"] == "ckpt.restore"]
    recover_stages = [e["stage"] for e in events
                      if e["ev"] == "guard.recover"]
    if after_kill:
        assert restores == []
        assert recover_stages == []
    else:
        assert {e["step"] for e in restores} == {1}
        if guard_recover:
            # the detect-and-recover ladder left its full story: typed
            # detections, the escalation restore, then success
            assert [e for e in events if e["ev"] == "guard.sdc"]
            assert "error" in recover_stages
            assert "restore" in recover_stages
            assert recover_stages[-1] == "recovered", recover_stages


@pytest.mark.chaos
def test_kill_mid_checkpoint_write_restarts_from_last_committed(tmp_path):
    """A worker SIGKILLed mid-checkpoint-write (torn third block, via the
    ``io.write_block`` injection point) leaves the previous checkpoint
    restorable: ``latest_valid()`` skips the torn one and the recovered
    global array is bit-identical (single-process workers)."""
    _run_kill_sequence(tmp_path, 1, 1, 1)


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_mid_checkpoint_write_multiprocess(tmp_path):
    """4 ``jax.distributed`` processes all SIGKILLed mid-collective-write
    (each tears its second block); recovery under a DIFFERENT process
    count (2) restores the last committed checkpoint bit-for-bit."""
    _run_kill_sequence(tmp_path, 4, 4, 2)


# ---------------------------------------------------------------------------
# cluster coordination drills (PR 6): consensus / leases / epochs over
# the FileKV backend — N plain OS processes, no jax.distributed needed
# ---------------------------------------------------------------------------

def _launch_cluster_phase(tmp_path, world, phase, expect_kill_rank=None):
    """Run one ``cluster_worker.py`` phase across ``world`` plain OS
    processes sharing a FileKV namespace.  ``expect_kill_rank`` names
    the one rank that must die by SIGKILL (the fault-injection victim);
    every other rank must exit 0 with the phase sentinel."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "cluster_worker.py")
    kvroot = os.path.join(str(tmp_path), "kv")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(here)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, kvroot, str(world), str(rank),
             str(tmp_path), phase],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for rank in range(world)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        drained = list(outs)
        for p in procs[len(outs):]:
            try:
                out, _ = p.communicate(timeout=10)
            except Exception:
                out = ""
            drained.append(out or "")
        pytest.fail(f"cluster {phase} workers timed out (a coordination "
                    f"deadlock — exactly what the layer must prevent); "
                    f"captured output:\n" + "\n---\n".join(drained))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if rank == expect_kill_rank:
            assert p.returncode == -signal.SIGKILL, (
                f"victim rank {rank} expected SIGKILL, got "
                f"{p.returncode}:\n{out[-3000:]}")
            continue
        assert p.returncode == 0, (
            f"cluster {phase} rank {rank} failed:\n{out[-3000:]}")
        assert f"CLUSTER_OK phase={phase} rank={rank}" in out, out[-2000:]
    return outs


def _cluster_events(tmp_path):
    return _pa_obs_check(os.path.join(str(tmp_path), "obs"))


def _assert_cluster_sdc_timeline(tmp_path, world):
    """Acceptance (a): EVERY rank journaled the SAME verdict sequence
    and epochs — agreed retry, then agreed restore of the SAME step 1
    (rank 0's newest step is torn, so the mesh must not follow rank 1's
    local ``latest_valid() == 2``) — and the recover ladder ended in
    ``recovered`` everywhere."""
    events = _cluster_events(tmp_path)
    per_rank_actions, per_rank_epochs = {}, {}
    for r in range(world):
        verdicts = [e for e in events if e["ev"] == "cluster.verdict"
                    and e["proc"] == r]
        per_rank_actions[r] = [e["action"] for e in verdicts]
        per_rank_epochs[r] = [e["epoch"] for e in verdicts]
        restores = {e["step"] for e in events
                    if e["ev"] == "ckpt.restore" and e["proc"] == r}
        assert restores == {1}, (r, restores)
        elect = [e for e in events if e["ev"] == "cluster.verdict"
                 and e["proc"] == r and e["action"] == "elect"]
        assert [e["step"] for e in elect] == [1], (r, elect)
        stages = [e["stage"] for e in events
                  if e["ev"] == "guard.recover" and e["proc"] == r]
        assert stages[-1] == "recovered", (r, stages)
    # the SAME verdicts and the SAME epochs on every rank — the
    # one-agreed-action contract
    assert per_rank_actions[0] == ["retry", "restore", "elect", "ok"], \
        per_rank_actions
    assert all(per_rank_actions[r] == per_rank_actions[0]
               for r in range(world)), per_rank_actions
    assert all(per_rank_epochs[r] == per_rank_epochs[0]
               for r in range(world)), per_rank_epochs
    # rank 1's poisoned exchanges were journaled as faults + detections
    sdc = [e for e in events if e["ev"] == "guard.sdc"]
    assert sdc and all(e["proc"] == 1 for e in sdc), sdc
    _assert_sdc_trace(tmp_path, world)


def _assert_sdc_trace(tmp_path, world):
    """PR 7 acceptance: ``pa-obs trace`` over the SDC drill artifacts
    emits a Perfetto-loadable trace_event JSON whose per-rank tracks
    carry the hop spans, rank 1's injected fault, every rank's recovery
    ladder, and the shared epoch markers — all joined on identical
    ``(step_idx, epoch)`` correlation keys on every rank."""
    import json

    from pencilarrays_tpu.obs.__main__ import main

    obs_dir = os.path.join(str(tmp_path), "obs")
    out = os.path.join(str(tmp_path), "trace.json")
    assert main(["trace", obs_dir, "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    assert sorted(trace["otherData"]["ranks"]) == list(range(world))
    join = {}
    for r in range(world):
        mine = [e for e in evs if e.get("pid") == r and e.get("ph") != "M"]
        hops = [e for e in mine if e.get("ph") == "X"
                and e["name"].startswith("hop ")]
        assert hops, f"rank {r}: no hop spans on its track"
        assert all("dur" in e and e["dur"] > 0 for e in hops), hops
        stages = {e["name"].split(":", 1)[1] for e in mine
                  if e["name"].startswith("recover:")}
        # every rank ran the agreed ladder; the failing rank also
        # journaled its detections as `error` stages
        assert {"retry", "restore", "recovered"} <= stages, (r, stages)
        if r == 1:
            assert "error" in stages, stages
        epochs = [e for e in mine if e["name"].startswith("epoch ")]
        assert epochs and all(e.get("s") == "g" for e in epochs), \
            (r, epochs)
        exchanges = [e for e in mine
                     if (e.get("ph") == "X"
                         and e["name"].startswith("hop "))
                     or e["name"] == "fault hop.exchange:corrupt"
                     or e["name"].startswith("guard.sdc")]
        join[r] = {
            # each attempt's exchange activity: a clean hop span, or —
            # on the poisoned rank — the fault/SDC markers that replaced
            # it (a detected attempt raises before the hop tap)
            "attempts": {(e["args"]["step_idx"], e["args"]["epoch"])
                         for e in exchanges},
            "hops": {(e["args"]["step_idx"], e["args"]["epoch"])
                     for e in hops},
            "epochs": {(e["args"]["step_idx"], e["args"]["epoch"],
                        e["name"]) for e in epochs},
        }
    faults = [e for e in evs if e.get("pid") == 1
              and e["name"] == "fault hop.exchange:corrupt"]
    assert faults, "rank 1's injected fault is missing from its track"
    # THE join contract: identical (step, epoch) keys on every rank —
    # every attempt rank 0 dispatched lines up with what the poisoned
    # rank was doing at that exact (step, epoch), the shared epoch
    # markers carry the same keys everywhere, and the agreed
    # post-restore rerun is a clean hop span on ALL ranks
    final = max(join[0]["attempts"])
    for r in range(1, world):
        assert join[r]["attempts"] == join[0]["attempts"], join
        assert join[r]["epochs"] == join[0]["epochs"], join
        assert final in join[r]["hops"], join


def _assert_cluster_kill_timeline(tmp_path, world, victim):
    """Acceptance (b): the victim's kill firing was journaled from
    inside the dying process; every survivor journaled the lease expiry
    naming the victim and wrote a peer-failure crash bundle."""
    events = _cluster_events(tmp_path)
    kills = [e for e in events if e["ev"] == "fault" and e["mode"] == "kill"]
    assert kills and all(e["proc"] == victim and e["point"] == "hop.exchange"
                         for e in kills), kills
    for r in range(world):
        if r == victim:
            continue
        expired = [e for e in events if e["ev"] == "cluster.lease"
                   and e["proc"] == r and e["status"] == "expired"]
        assert expired and all(e["rank"] == victim for e in expired), \
            (r, expired)
        bundles = [e for e in events if e["ev"] == "guard.bundle"
                   and e["proc"] == r and e["reason"] == "peer-failure"]
        assert bundles, r


def _run_cluster_sequence(tmp_path, world):
    victim = max(0, world - 2)
    _launch_cluster_phase(tmp_path, world, "sdc")
    _assert_cluster_sdc_timeline(tmp_path, world)
    outs = _launch_cluster_phase(tmp_path, world, "kill",
                                 expect_kill_rank=victim)
    # survivors detected the death by LEASE EXPIRY (ttl 2 s), far below
    # the 60 s verdict timeout and the 300 s watchdog — the whole point
    for out in outs:
        m = re.search(r"detect_s=([0-9.]+)", out)
        if m:
            assert float(m.group(1)) < 20.0, out
    _assert_cluster_kill_timeline(tmp_path, world, victim)
    _launch_cluster_phase(tmp_path, world, "restore")


@pytest.mark.chaos
def test_cluster_coordinated_recovery(tmp_path):
    """2-rank FileKV drill of the full coordination ladder: one rank's
    injected SDC → mesh-agreed retry → mesh-agreed restore of the SAME
    elected step (the other rank's newest step is torn) → bit-identical
    rerun; one rank SIGKILLed mid-step → the survivor exits with typed
    ``PeerFailureError`` + crash bundle within the lease deadline; a
    fresh incarnation's coordinated restore is bit-identical."""
    _run_cluster_sequence(tmp_path, 2)


@pytest.mark.slow
@pytest.mark.chaos
def test_cluster_coordinated_recovery_4proc(tmp_path):
    """The 4-rank variant of the drill (the ISSUE's acceptance shape:
    rank 2 is the SIGKILL victim, three survivors must all detect it)."""
    _run_cluster_sequence(tmp_path, 4)


# ---------------------------------------------------------------------------
# elastic mesh reformation drills (ISSUE 8): SIGKILL one rank mid-step →
# survivors shrink, re-plan, restore the agreed step, and FINISH
# ---------------------------------------------------------------------------

_FINAL_RE = re.compile(r"FINAL=([0-9a-f]{64})")


def _final_digest(out):
    m = _FINAL_RE.search(out)
    assert m, f"no FINAL digest in worker output:\n{out[-2000:]}"
    return m.group(1)


def _assert_elastic_timeline(tmp_path, world, victim):
    """The ``pa-obs``-linted reformation story, per survivor: the
    victim's kill journaled from inside the dying process; lease expiry
    naming the victim; one reform sequence begin→membership→mesh→
    replan→restore→complete agreeing on the survivor set; the epoch
    bump attributed to the reformation; the agreed step-2 restore; the
    recover ladder ending ``recovered via=reform``; and NO post-reform
    wreckage (no further expiries, no second reformation) — the mesh
    simply finished the run."""
    events = _cluster_events(tmp_path)
    kills = [e for e in events if e["ev"] == "fault" and e["mode"] == "kill"]
    assert kills and all(e["proc"] == victim and e["point"] == "hop.exchange"
                         for e in kills), kills
    survivors = [r for r in range(world) if r != victim]
    for r in survivors:
        mine = [e for e in events if e.get("proc") == r]
        expired = [e for e in mine if e["ev"] == "cluster.lease"
                   and e["status"] == "expired"]
        assert expired and all(e["rank"] == victim for e in expired), \
            (r, expired)
        stages = [e["stage"] for e in mine if e["ev"] == "cluster.reform"]
        assert stages.count("begin") == 1, (r, stages)
        assert stages.count("complete") == 1, (r, stages)
        for a, b in zip(("begin", "membership", "mesh", "replan",
                         "restore", "complete"),
                        ("membership", "mesh", "replan", "restore",
                         "complete", None)):
            if b is not None:
                assert stages.index(a) < stages.index(b), (r, stages)
        memb = [e for e in mine if e["ev"] == "cluster.reform"
                and e["stage"] == "membership"]
        assert memb[0]["members"] == survivors, (r, memb)
        assert memb[0]["new_world"] == world - 1, (r, memb)
        drops = [(e["rank"], e["change"]) for e in mine
                 if e["ev"] == "cluster.member"]
        assert (victim, "drop") in drops, (r, drops)
        # the epoch bump is attributed to the reformation
        bumps = [e for e in mine if e["ev"] == "guard.epoch"]
        assert any(str(e.get("reason", "")).startswith("reform:")
                   for e in bumps), (r, bumps)
        # the agreed restore: step 2 (steps 0-2 committed pre-kill)
        assert {e["step"] for e in mine if e["ev"] == "ckpt.restore"} \
            == {2}, r
        rec = [(e["stage"], e.get("via")) for e in mine
               if e["ev"] == "guard.recover"]
        assert ("reform", None) in rec, (r, rec)
        assert ("recovered", "reform") in rec, (r, rec)
        # every step committed: the run FINISHED after the reformation
        commits = {e["step"] for e in mine if e["ev"] == "ckpt.commit"}
        assert commits == {0, 1, 2, 3, 4}, (r, commits)
        # no post-reform wreckage
        done = next(i for i, e in enumerate(mine)
                    if e["ev"] == "cluster.reform"
                    and e["stage"] == "complete")
        post = mine[done + 1:]
        assert not [e for e in post if e["ev"] == "cluster.lease"
                    and e["status"] == "expired"], r
        assert not [e for e in post if e["ev"] == "cluster.reform"], r
        assert not [e for e in post if e["ev"] == "guard.bundle"], r
    # the victim's journal stops before any reformation record
    assert not [e for e in events if e.get("proc") == victim
                and e["ev"] == "cluster.reform"]


def _run_elastic_sequence(tmp_path, world):
    victim = world - 1
    ref = tmp_path / "ref"
    el = tmp_path / "el"
    ref.mkdir()
    el.mkdir()
    ref_outs = _launch_cluster_phase(ref, world, "elastic_ref")
    finals = {_final_digest(out) for out in ref_outs}
    assert len(finals) == 1, finals     # the reference is deterministic
    ref_final = finals.pop()
    el_outs = _launch_cluster_phase(el, world, "elastic",
                                    expect_kill_rank=victim)
    for rank, out in enumerate(el_outs):
        if rank == victim:
            continue
        assert _final_digest(out) == ref_final, (
            f"rank {rank}: post-reformation output differs from the "
            f"never-killed reference:\n{out[-2000:]}")
        # ISSUE 9 satellite: the registered BATCHED plan was rebuilt by
        # the reformation with its batch intact (worker-side asserts
        # batch_dims and a batched forward; the marker line proves the
        # factory actually re-ran on every survivor)
        assert "REPLAN_BATCH=3" in out, (
            f"rank {rank}: reformed batched plan marker missing:\n"
            f"{out[-2000:]}")
        # ISSUE 10 satellite: the SERVED plan (registered through
        # serve.PlanService.register_plan -> elastic.register_plan)
        # rebuilt through the reformation and the service resumed
        # draining its pre-kill queue — both host-payload requests
        # re-bound to the rebuilt plan and completed bit-identically
        # (worker-side asserts; the marker proves the drain happened)
        assert "SERVE_RESUMED=2" in out, (
            f"rank {rank}: served-plan resume marker missing:\n"
            f"{out[-2000:]}")
    _assert_elastic_timeline(el, world, victim)


@pytest.mark.chaos
def test_elastic_reformation_survives_rank_loss(tmp_path):
    """ISSUE 8 acceptance: 2-rank FileKV drill — rank 1 SIGKILLed
    mid-step → rank 0 reforms to world=1, restores the agreed
    epoch-stamped step, and produces bit-identical final output vs an
    unkilled reference run, with the full detect→reform→restore→resume
    sequence lint-clean on the pa-obs timeline."""
    _run_elastic_sequence(tmp_path, 2)


@pytest.mark.slow
@pytest.mark.chaos
def test_elastic_reformation_4rank(tmp_path):
    """The 4-rank variant: three survivors run the membership consensus
    together, reform to world=3 with dense reindexing, and all finish
    bit-identically."""
    _run_elastic_sequence(tmp_path, 4)


# ---------------------------------------------------------------------------
# overload-resilient serving drills (ISSUE 15): storm shedding + SIGKILL
# mid-storm, and the autoscaler's scale-down -> rejoin round trip
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_overload_storm_sheds_and_survives_kill(tmp_path):
    """ISSUE 15 acceptance: an overload storm against the 2-rank FileKV
    mesh sheds EXACTLY the sheddable tenants (typed at submit, the
    protected tenant's requests all complete under deadline and
    bit-identical to unloaded execution), and rank 1 SIGKILLed
    mid-storm triggers reform + resumed draining with every submitted
    request resolved exactly once — result, typed DeadlineError, or
    typed AdmissionError; no stranded waiter, no silent late answer."""
    outs = _launch_cluster_phase(tmp_path, 2, "storm",
                                 expect_kill_rank=1)
    out0 = outs[0]
    assert "STORM_SHED=4" in out0       # all 4 sheddable, typed
    assert "STORM_OK=4" in out0         # all 4 protected, bit-identical
    assert "STORM_SHED=4" in outs[1]    # the victim shed too, pre-kill
    assert _FINAL_RE.search(out0)
    events = _cluster_events(tmp_path)
    # the kill was journaled from inside the dying rank's dispatch
    kills = [e for e in events
             if e["ev"] == "fault" and e["mode"] == "kill"]
    assert kills and all(e["proc"] == 1 and e["point"] == "hop.exchange"
                         for e in kills), kills
    # the pressure gate's transition is on the record, on BOTH ranks
    press = [e for e in events if e["ev"] == "serve.pressure"]
    assert {e["proc"] for e in press} == {0, 1}, press
    assert all(e["state"] in ("shed", "evict") for e in press
               if e["prev"] == "ok"), press
    assert all(e.get("projection", {}).get("drain_s") is not None
               for e in press), "transitions must carry the projection"
    # the survivor reformed: replan -> engine AFTER restore-stage
    # (hold-until-commit, satellite 1) -> complete, then recovered
    stages = [e["stage"] for e in events
              if e["ev"] == "cluster.reform" and e["proc"] == 0]
    assert "complete" in stages, stages
    assert stages.index("replan") < stages.index("engine") \
        < stages.index("complete"), stages
    rec = [(e["stage"], e.get("via")) for e in events
           if e["ev"] == "guard.recover" and e["proc"] == 0]
    assert ("recovered", "reform") in rec, rec
    # exactly-once resolution: warmup + 4 protected = 5 ok completes
    # on the survivor, unique request ids, zero SLO violations; the 4
    # shed requests are typed submit rejections (counters, no tickets)
    comp0 = [e for e in events
             if e["ev"] == "serve.complete" and e["proc"] == 0]
    assert len(comp0) == 5 and len({e["req"] for e in comp0}) == 5, comp0
    assert all(e["outcome"] == "ok" for e in comp0), comp0
    assert not [e for e in events if e["ev"] == "serve.slo_violation"]


@pytest.mark.chaos
def test_scale_round_trip_through_real_joiner(tmp_path):
    """ISSUE 15 acceptance: scale-down -> scale-up round-trips through
    a REAL joiner.  Idle windows make every rank journal the same
    ``serve.scale`` down decision (only the highest rank acts =
    announce_leave), the survivor reforms down; the departed process
    returns as a pre-warmed joiner (plans compiled through the
    persistent cache before the join) admitted by the survivor's
    scale-up reformation — every decision journaled with its
    projection inputs."""
    outs = _launch_cluster_phase(tmp_path, 2, "scale")
    out0, out1 = outs
    assert "SCALE_DOWN world=1" in out0
    assert re.search(r"SCALE_UP gen=\d+", out0), out0[-2000:]
    m = re.search(r"SCALE_JOINED gen=(\d+) rank=1 warm_s=([0-9.]+)",
                  out1)
    assert m, out1[-2000:]
    events = _cluster_events(tmp_path)
    scale = [e for e in events if e["ev"] == "serve.scale"]
    tup = {(e["proc"], e["direction"], e.get("reason"), e.get("acted"))
           for e in scale}
    assert (1, "down", "idle", True) in tup, tup      # the leaver acted
    assert (0, "down", "idle", False) in tup, tup     # same decision,
    # journaled on the non-leaver too
    assert (0, "up", "overload", True) in tup, tup    # admitted joiner
    assert (1, "up", "prewarm", False) in tup, tup    # measured warmup
    assert all("projection" in e for e in scale), scale
    # two reformations on the survivor, in order: the planned
    # departure, then the scale-up join admission
    begins = [e.get("reason") for e in events
              if e["ev"] == "cluster.reform" and e["stage"] == "begin"
              and e["proc"] == 0]
    assert begins == ["leave", "scale-up"], begins
    completes = [e for e in events if e["ev"] == "cluster.reform"
                 and e["stage"] == "complete" and e["proc"] == 0]
    assert len(completes) == 2, completes
    # the joiner's admission is a member join record
    joins = [e for e in events if e["ev"] == "cluster.member"
             and e["change"] == "join"]
    assert joins and all(e["rank"] == 1 for e in joins), joins
    # the departure was planned: no crash bundles, no peer-failure
    assert not [e for e in events if e["ev"] == "guard.bundle"]


@pytest.mark.chaos
def test_cluster_straggler_detection(tmp_path):
    """PR 7 acceptance: a ``hop.exchange:delay%rank1`` fault on a
    2-rank FileKV mesh produces exactly ONE ``cluster.straggler`` event
    naming rank 1 with the measured excess (emitted by rank 0's mesh
    fold, deduplicated across cadence ticks), and the undelayed control
    run produces ZERO straggler events."""
    straggle = tmp_path / "straggle"
    control = tmp_path / "control"
    straggle.mkdir()
    control.mkdir()

    _launch_cluster_phase(straggle, 2, "straggle")
    events = _cluster_events(straggle)
    flags = [e for e in events if e["ev"] == "cluster.straggler"]
    assert len(flags) == 1, flags
    f = flags[0]
    assert f["rank"] == 1 and f["proc"] == 0, f    # rank 0 names rank 1
    # the injected drag is 0.3 s; the measured excess must carry most
    # of it (baseline = rank 0's undelayed dispatch, a few ms)
    assert f["excess_s"] > 0.1, f
    assert f["baseline_s"] < f["excess_s"], f
    delays = [e for e in events
              if e["ev"] == "fault" and e["mode"] == "delay"]
    assert delays and all(e["proc"] == 1 for e in delays), delays
    # the live fold published the mesh artifacts next to the journal
    mesh = os.path.join(str(straggle), "obs", "mesh_metrics.json")
    assert os.path.exists(mesh)
    import json

    with open(mesh) as fh:
        fold = json.load(fh)
    assert fold["missing_ranks"] == [] and fold["ranks"] == [0, 1]
    with open(os.path.join(str(straggle), "obs",
                           "mesh_metrics.prom")) as fh:
        prom = fh.read()
    assert 'rank="0"' in prom and 'rank="1"' in prom

    _launch_cluster_phase(control, 2, "control")
    events = _cluster_events(control)
    assert [e for e in events if e["ev"] == "cluster.straggler"] == []
    assert [e for e in events if e["ev"] == "fault"] == []
