"""Observability subsystem: journal round-trip + schema, disabled-path
no-op, TimerOutput thread-safety regression, drift arithmetic, exporters.

The contracts under test (ISSUE 3 acceptance):

* with ``PENCILARRAYS_TPU_OBS`` unset nothing is written, created or
  allocated — instrumented hot paths stay no-op;
* with it set, one run produces a JSONL journal whose every record
  passes the schema lint (``obs/schema.py``), plus a metrics snapshot
  carrying per-hop predicted-vs-measured drift;
* ``TimerOutput`` survives concurrent use (the PR-2 checksum thread
  pool corrupted the old shared stack) and merges across timers;
* the drift tracker's fitted-bandwidth arithmetic is exact on synthetic
  timings.
"""

import json
import os
import threading

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.obs import drift as obs_drift
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.utils.timers import TimerOutput


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    """Every test starts disabled with empty registries and no journal."""
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    monkeypatch.delenv("PENCILARRAYS_TPU_OBS_DIR", raising=False)
    monkeypatch.delenv("PENCILARRAYS_TPU_OBS_FSYNC", raising=False)
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    obs_drift.drift_tracker.reset()
    yield
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    obs_drift.drift_tracker.reset()


def _mk_pencils():
    topo = pa.Topology((2, 4))
    pen_x = pa.Pencil(topo, (9, 12, 10), (1, 2))
    pen_y = pa.Pencil(topo, (9, 12, 10), (0, 2))
    return pen_x, pen_y


# ---------------------------------------------------------------------------
# disabled path: strict no-op
# ---------------------------------------------------------------------------


def test_disabled_is_noop(tmp_path):
    assert not obs.enabled()
    assert obs.record_event("hop", method="AllToAll") is False
    # instrumented operations must neither create files nor metrics
    pen_x, pen_y = _mk_pencils()
    u = pa.PencilArray.zeros(pen_x)
    pa.transpose(u, pen_y)
    assert not os.path.exists(obs_events.DEFAULT_DIR)
    snap = obs.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}
    assert snap["drift"]["hops"] == {}


def test_env_gate_re_read_on_change(tmp_path, monkeypatch):
    """Workers arm observability after import (the faults.py contract)."""
    assert not obs.enabled()
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "j"))
    assert obs.enabled()
    assert obs.journal_dir() == str(tmp_path / "j")
    monkeypatch.setenv(obs.ENV_VAR, "0")
    assert not obs.enabled()


# ---------------------------------------------------------------------------
# journal round-trip + schema
# ---------------------------------------------------------------------------


def test_journal_roundtrip_and_schema(tmp_path, monkeypatch):
    """One instrumented run -> parseable, schema-clean, ordered journal
    containing the event families the flight recorder promises."""
    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    from pencilarrays_tpu.ops.fft import PencilFFTPlan
    from pencilarrays_tpu.resilience import (CheckpointManager, RetryPolicy,
                                             faults)

    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True, pipeline=2)
    plan.backward(plan.forward(plan.allocate_input()))
    pen_x, pen_y = _mk_pencils()
    pa.transpose(pa.PencilArray.zeros(pen_x), pen_y)
    mgr = CheckpointManager(
        str(tmp_path / "ck"), keep=2,
        retry=RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0))
    state = {"u": pa.PencilArray.from_global(
        pen_x, np.arange(9 * 12 * 10, dtype=np.float32).reshape(9, 12, 10))}
    with faults.active("io.open:error*1@1"):
        mgr.save(0, state)  # first open errors -> fault + retry events
    mgr.restore().read("u", pen_x)

    events = obs.read_journal(jdir)
    assert obs.lint_journal(events) == []
    kinds = {e["ev"] for e in events}
    for required in ("run.start", "plan.build", "hop", "io.open", "io.write",
                     "ckpt.save", "ckpt.commit", "ckpt.verify",
                     "ckpt.restore", "fault", "retry"):
        assert required in kinds, f"missing {required} in {sorted(kinds)}"
    # common envelope: one run id, per-process monotonic seq
    runs = {e["run"] for e in events}
    assert len(runs) == 1
    seqs = [e["seq"] for e in events if e["proc"] == 0]
    assert seqs == sorted(seqs)
    # the save timeline is ordered: begin < commit < committed-status
    t_begin = next(e["t_mono"] for e in events
                   if e["ev"] == "ckpt.save" and e["status"] == "begin")
    t_commit = next(e["t_mono"] for e in events if e["ev"] == "ckpt.commit")
    t_done = next(e["t_mono"] for e in events
                  if e["ev"] == "ckpt.save" and e["status"] == "committed")
    assert t_begin < t_commit < t_done
    # fault fired at the io.open point, retry references the same label
    fault = next(e for e in events if e["ev"] == "fault")
    assert fault["point"] == "io.open" and fault["mode"] == "error"
    retry = next(e for e in events if e["ev"] == "retry")
    assert retry["attempt"] == 1 and "InjectedFault" in retry["error"]
    # hop events carry the cost-model prediction
    hop = next(e for e in events if e["ev"] == "hop" and e["r"] is not None)
    assert hop["predicted_bytes"] > 0 and hop["dispatch_s"] >= 0


def test_journal_survives_torn_line(tmp_path, monkeypatch):
    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    obs.record_event("run.stop")
    path = os.path.join(jdir, "journal.r0.jsonl")
    with open(path, "a") as f:
        f.write('{"v":1,"ev":"hop","tor')  # the torn tail of a crash
    events = obs.read_journal(jdir)
    assert [e["ev"] for e in events] == ["run.start", "run.stop"]


def test_schema_lint_catches_drift(tmp_path):
    ok = {"v": 1, "ev": "fault", "run": "r", "proc": 0, "seq": 1,
          "t_wall": 0.0, "t_mono": 0.0, "point": "io.open",
          "mode": "error", "hit": 1}
    assert obs.lint_event(ok) == []
    unknown = dict(ok, ev="not.registered")
    assert any("unknown event type" in e for e in obs.lint_event(unknown))
    missing = dict(ok)
    del missing["point"]
    assert any("missing required field 'point'" in e
               for e in obs.lint_event(missing))
    torn = dict(ok)
    del torn["seq"]
    assert any("missing common field" in e for e in obs.lint_event(torn))


def test_metrics_snapshot_and_prometheus(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    obs.counter("test.count", kind="a").inc(3)
    obs.gauge("test.gauge").set(2.5)
    h = obs.histogram("test.seconds")
    for v in (0.5, 1.5, 4.0):
        h.observe(v)
    snap = obs.snapshot()
    assert snap["counters"]["test.count{kind=a}"] == 3
    assert snap["gauges"]["test.gauge"] == 2.5
    hs = snap["histograms"]["test.seconds"]
    assert hs["count"] == 3 and hs["min"] == 0.5 and hs["max"] == 4.0
    assert hs["mean"] == pytest.approx(2.0)
    assert "slope_fallback" in snap["benchtime"]  # the bench noise floor
    # snapshot is JSON-serializable and atomically publishable
    path = obs.write_snapshot()
    with open(path) as f:
        assert json.load(f)["counters"]["test.count{kind=a}"] == 3
    text = obs.to_prometheus()
    assert 'pa_test_count_total{kind="a"} 3' in text
    assert "pa_test_gauge 2.5" in text
    assert "pa_test_seconds_count 3" in text
    pp = obs.write_prometheus(str(tmp_path / "metrics.prom"))
    with open(pp) as f:
        assert f.read() == text


# ---------------------------------------------------------------------------
# TimerOutput thread-safety regression + merge
# ---------------------------------------------------------------------------


def test_timer_output_concurrent_nesting():
    """The pre-obs TimerOutput shared ONE mutable stack: concurrent
    ``timeit`` blocks interleaved push/pop and corrupted the tree (the
    PR-2 checksum pool dispatches concurrently).  The stack is now
    thread-local; every nested call must land under its own parent with
    exact counts."""
    t = TimerOutput("conc")
    NT, REPS = 8, 200
    errors = []

    def worker():
        try:
            for _ in range(REPS):
                with t("outer"):
                    with t("inner"):
                        pass
        except Exception as e:  # pre-fix: IndexError / wrong nesting
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(NT)]
    for th in threads:
        th.start()
    # reading WHILE timing must never crash (merge snapshots racy
    # children with a bounded retry, not a lock on the hot path)
    for _ in range(50):
        t.report()
        t.snapshot()
    for th in threads:
        th.join()
    assert errors == []
    snap = t.snapshot()
    outer = snap["children"]["outer"]
    assert outer["ncalls"] == NT * REPS
    assert outer["children"]["inner"]["ncalls"] == NT * REPS
    assert "inner" not in snap["children"]  # nesting never flattened


def test_timer_output_merge_cross_timer_and_snapshot():
    a, b = TimerOutput("a"), TimerOutput("b")
    with a("transpose!"):
        pass
    with b("transpose!"):
        with b("pack data"):
            pass
    a.merge(b)
    snap = a.snapshot()
    assert snap["children"]["transpose!"]["ncalls"] == 2
    assert snap["children"]["transpose!"]["children"][
        "pack data"]["ncalls"] == 1
    # cross-process wire format: a peer ships snapshot(), proc0 merges
    c = TimerOutput("c").merge(json.loads(json.dumps(b.snapshot())))
    assert c.snapshot()["children"]["transpose!"]["ncalls"] == 1


def test_timer_output_thread_churn_is_bounded():
    """Short-lived threads (the I/O layer spawns a pool per write) must
    not grow timer state without bound — dead threads' trees fold into
    the retired accumulator, losing nothing."""
    t = TimerOutput("churn")

    def one_shot():
        with t("w"):
            pass

    for _ in range(50):
        th = threading.Thread(target=one_shot)
        th.start()
        th.join()
    snap = t.snapshot()  # prunes dead-thread roots
    assert snap["children"]["w"]["ncalls"] == 50
    assert len(t._roots) <= 1  # only (at most) the caller's root remains
    assert t.snapshot()["children"]["w"]["ncalls"] == 50  # idempotent


def test_timer_output_reset_under_threads():
    t = TimerOutput("r")
    with t("s"):
        pass
    t.reset()
    assert t.snapshot()["children"] == {}
    with t("s2"):
        pass
    assert t._root.children["s2"].ncalls == 1  # back-compat accessor


# ---------------------------------------------------------------------------
# drift tracker arithmetic (synthetic timings)
# ---------------------------------------------------------------------------


def test_drift_arithmetic_synthetic():
    tr = obs_drift.DriftTracker()
    tr.record("A", 100, 1.0, source="benchtime")
    tr.record("B", 300, 3.0, source="benchtime")
    rep = tr.report()
    # byte-weighted fit: (100+300) bytes / (1+3) s = 100 B/s, zero drift
    assert rep["fitted_bytes_per_s"] == pytest.approx(100.0)
    assert rep["hops"]["A"]["drift"] == pytest.approx(1.0)
    assert rep["hops"]["B"]["drift"] == pytest.approx(1.0)
    # min-tracking: a slower repeat must not move the representative
    tr.record("B", 300, 9.0, source="benchtime")
    rep = tr.report()
    assert rep["hops"]["B"]["measured_s"] == pytest.approx(3.0)
    assert rep["hops"]["B"]["count"] == 2
    assert rep["hops"]["B"]["last_s"] == pytest.approx(9.0)
    # a hop 3x over its byte-predicted time drifts to exactly 15/7
    tr.record("C", 100, 3.0, source="benchtime")
    rep = tr.report()
    assert rep["fitted_bytes_per_s"] == pytest.approx(500.0 / 7.0)
    assert rep["hops"]["C"]["drift"] == pytest.approx(15.0 / 7.0)
    assert rep["hops"]["A"]["drift"] == pytest.approx(5.0 / 7.0)


def test_drift_source_ranking_and_zero_bytes():
    tr = obs_drift.DriftTracker()
    tr.record("A", 100, 50.0, source="dispatch")
    tr.record("A", 100, 1.0, source="benchtime")
    tr.record("A", 100, 70.0, source="dispatch")
    rep = tr.report()
    assert rep["hops"]["A"]["source"] == "benchtime"
    assert rep["hops"]["A"]["measured_s"] == pytest.approx(1.0)
    # local permute: nothing on the wire, drift undefined (never inf)
    tr.record("L", 0, 1.0, source="dispatch")
    rep = tr.report()
    assert rep["hops"]["L"]["drift"] is None
    with pytest.raises(ValueError):
        tr.record("A", 1, 1.0, source="bogus")


def test_no_trace_time_hop_events_under_jit(tmp_path, monkeypatch):
    """transpose() inside a user jit runs the tap at TRACE time: it must
    journal nothing (one event per compile would misrepresent thousands
    of executions) and feed no lowering-time garbage to the drift fit."""
    import jax

    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    pen_x, pen_y = _mk_pencils()
    u = pa.PencilArray.zeros(pen_x)

    @jax.jit
    def step(d):
        return pa.transpose(pa.PencilArray(pen_x, d), pen_y).data

    for _ in range(3):
        step(u.data)
    assert [e for e in obs.read_journal() if e["ev"] == "hop"] == []
    assert obs.drift_report()["hops"] == {}


def test_drift_fits_are_per_source_class():
    """A dispatch sample is a LOWER bound on wire time (enqueue only):
    it must be fitted among dispatch samples and never pollute the
    device-protocol fit (one enqueue-timed hop in a shared fit would
    invert every other hop's verdict)."""
    tr = obs_drift.DriftTracker()
    tr.record("D1", 100, 0.001, source="dispatch")   # absurdly fast
    tr.record("T1", 100, 1.0, source="benchtime")
    rep = tr.report()
    assert rep["fitted_bytes_per_s"] == pytest.approx(100.0)
    assert rep["dispatch_fitted_bytes_per_s"] == pytest.approx(1e5)
    assert rep["hops"]["T1"]["drift"] == pytest.approx(1.0)  # unpolluted
    assert rep["hops"]["D1"]["drift"] == pytest.approx(1.0)


def test_io_op_journals_failures_honestly(tmp_path, monkeypatch):
    """A raising driver operation lands in the journal as failed, and
    its bytes are NOT counted as written."""
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    with pytest.raises(RuntimeError, match="boom"):
        with obs.io_op("io.write", "BinaryDriver", "/nowhere", "u", 1000):
            raise RuntimeError("boom")
    ev = next(e for e in obs.read_journal() if e["ev"] == "io.write")
    assert ev["ok"] is False and "boom" in ev["error"]
    assert ev["bytes"] == 1000  # the intended size, for the post-mortem
    snap = obs.snapshot()
    assert "io.bytes_written{driver=BinaryDriver}" not in snap["counters"]
    assert obs.lint_journal(obs.read_journal()) == []


def test_dispatch_feeds_drift_and_measure_transpose(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    pen_x, pen_y = _mk_pencils()
    u = pa.PencilArray.zeros(pen_x)
    pa.transpose(u, pen_y)
    rep = obs.drift_report()
    assert len(rep["hops"]) == 1
    (hop, entry), = rep["hops"].items()
    assert "AllToAll" in hop and entry["source"] == "dispatch"
    assert entry["predicted_bytes"] > 0
    # the benchtime-protocol entry point upgrades the hop's source
    out = obs_drift.measure_transpose(u, pen_y, k0=1, k1=2, repeats=1)
    assert out["predicted_bytes"] == entry["predicted_bytes"]
    rep = obs.drift_report()
    assert rep["hops"][hop]["source"] == "benchtime"
    snap = obs.snapshot()
    assert snap["drift"]["hops"][hop]["source"] == "benchtime"
    # benchtime satellites: measurement count + spread landed as metrics
    assert snap["counters"]["benchtime.measurements"] >= 1
    assert "drift.sample" in {e["ev"] for e in obs.read_journal()}


# ---------------------------------------------------------------------------
# span / profile
# ---------------------------------------------------------------------------


def test_span_three_sinks(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    timer = TimerOutput("spans")
    pa.enable_debug_timings()
    try:
        with obs.span("drill section", timer=timer):
            pass
    finally:
        pa.disable_debug_timings()
    assert timer._root.children["drill section"].ncalls == 1
    snap = obs.snapshot()
    assert snap["histograms"]["span.seconds{label=drill section}"][
        "count"] == 1


def test_profile_stamps_capture_metadata(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    from pencilarrays_tpu.ops.fft import PencilFFTPlan

    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True)
    cap = str(tmp_path / "capture")
    with obs.profile(cap, plan=plan, note="unit test"):
        plan.forward(plan.allocate_input())
    with open(os.path.join(cap, "pa_capture_metadata.json")) as f:
        stamp = json.load(f)
    assert stamp["plan"]["transforms"] == list(plan.transforms)
    assert stamp["plan"]["predicted_costs"] == plan.collective_costs()
    assert stamp["metadata"]["note"] == "unit test"
    evs = [e for e in obs.read_journal() if e["ev"] == "profile"]
    assert [e["status"] for e in evs] == ["start", "stop"]
    assert obs.lint_journal(obs.read_journal()) == []
