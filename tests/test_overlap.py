"""Comm-compute overlap — the XLA re-specification of the reference's
``waitall=false`` + ``MPI.Waitany`` unpack pipeline
(``Transpositions.jl:142-158, 510-516``).

On TPU, overlap is owned by XLA's latency-hiding scheduler: collectives
lower to async ``-start``/``-done`` pairs and independent compute is
scheduled between them.  That rewrite happens in the TPU backend (the CPU
backend lowers collectives synchronously), so what these tests pin is the
property the scheduler NEEDS and that this library controls: a transpose
and unrelated compute placed in one jitted program are **data-dependency
free** — nothing in the traced program sequences the exchange against the
independent work, so the scheduler is free to overlap them.  Checked on
the jaxpr (the dependency graph XLA receives).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll, Pencil, PencilArray, PencilFFTPlan, Ring, Topology,
    Transposition, transpose,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def _eqn_deps(eqns):
    """Map eqn index -> set of eqn indices it transitively depends on."""
    producer = {}
    for j, e in enumerate(eqns):
        for v in e.outvars:
            producer[v] = j
    deps = []
    for e in eqns:
        seen = set()
        stack = [v for v in e.invars if type(v).__name__ != "Literal"]
        while stack:
            v = stack.pop()
            j = producer.get(v)
            if j is not None and j not in seen:
                seen.add(j)
                stack.extend(u for u in eqns[j].invars
                             if type(u).__name__ != "Literal")
        deps.append(seen)
    return deps


@pytest.mark.parametrize("method", [AllToAll(), Ring()])
def test_transpose_and_independent_compute_are_dependency_free(topo, method):
    """The scheduler's overlap precondition: in one traced program, the
    exchange neither depends on nor is depended on by the unrelated
    matmul."""
    pen_x = Pencil(topo, (16, 16, 16), (1, 2))
    pen_y = Pencil(topo, (16, 16, 16), (0, 2))
    x = PencilArray.zeros(pen_x)
    w = jnp.ones((64, 64))

    def f(d, m):
        y = transpose(PencilArray(pen_x, d), pen_y, method=method)
        z = m @ m  # independent work the scheduler may overlap
        return y.data, z

    jpr = jax.make_jaxpr(f)(x.data, w).jaxpr
    eqns = jpr.eqns
    t_idx = [i for i, e in enumerate(eqns)
             if "all_to_all" in str(e) or "ppermute" in str(e)]
    d_idx = [i for i, e in enumerate(eqns) if "dot_general" in str(e)
             and "all_to_all" not in str(e) and "ppermute" not in str(e)]
    assert t_idx and d_idx, (len(t_idx), len(d_idx))
    deps = _eqn_deps(eqns)
    for t in t_idx:
        for d in d_idx:
            assert t not in deps[d], "matmul depends on the exchange"
            assert d not in deps[t], "exchange depends on the matmul"

    # and both compile into ONE module (one dispatch, one schedule)
    hlo = jax.jit(f).lower(x.data, w).compile().as_text()
    assert "dot(" in hlo or "dot-general" in hlo


def _subjaxprs(jaxpr):
    """Yield ``jaxpr`` and every (closed) sub-jaxpr reachable from its
    eqn params, recursively."""
    yield jaxpr
    for e in jaxpr.eqns:
        for v in e.params.values():
            sub = getattr(v, "jaxpr", None)
            if sub is not None:
                yield from _subjaxprs(sub)
            elif hasattr(v, "eqns"):
                yield from _subjaxprs(v)


def _contains_fft(eqn):
    """True when the eqn is (or transitively wraps) an FFT primitive —
    jnp.fft calls trace as pjit-wrapped sub-jaxprs."""
    if eqn.primitive.name == "fft":
        return True
    for v in eqn.params.values():
        sub = getattr(v, "jaxpr", None)
        if sub is None and hasattr(v, "eqns"):
            sub = v
        if sub is not None and any(_contains_fft(i) for i in sub.eqns):
            return True
    return False


def test_fused_pipelined_hop_exchanges_independent_of_ffts(topo):
    """The tentpole's overlap precondition, INSIDE one fused hop: with
    ``PencilFFTPlan(pipeline=K)``, chunk ``k``'s exchange must have no
    dependency edge to any chunk's FFT stage (in particular chunk
    ``k-1``'s) — the serialized schedule's hop->transform barrier is
    gone and the latency-hiding scheduler may overlap chunk ``k``'s
    wire time with chunk ``k-1``'s transform.  Each chunk's FFT still
    depends on exactly its own chunk's exchange (that dependency is the
    data flow, not the barrier)."""
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32, pipeline=2)
    assert any(s[0] == "ft" for s in plan._steps), "no hop fused"
    x = plan.allocate_input()
    jpr = jax.make_jaxpr(
        lambda d: plan.forward(PencilArray(plan.input_pencil, d)).data
    )(x.data).jaxpr

    checked = 0
    for sj in _subjaxprs(jpr):
        eqns = list(sj.eqns)
        t_idx = [i for i, e in enumerate(eqns)
                 if e.primitive.name == "all_to_all"]
        f_idx = [i for i, e in enumerate(eqns) if _contains_fft(e)]
        if len(t_idx) < 2 or not f_idx:
            continue  # not a fused hop body
        checked += 1
        deps = _eqn_deps(eqns)
        # no exchange ever waits on a transform ...
        for t in t_idx:
            for f in f_idx:
                assert f not in deps[t], (
                    "chunk exchange depends on an FFT stage — the fused "
                    "hop reintroduced the barrier")
        # ... and each chunk's transform consumes exactly one exchange
        for f in f_idx:
            assert len([t for t in t_idx if t in deps[f]]) == 1
    assert checked >= 1, "no fused hop body found in the jaxpr"


def test_fused_pipelined_backward_ffts_independent_of_exchanges(topo):
    """Mirror property for :meth:`backward`: the inverse transform of
    chunk ``k`` must not depend on any chunk's exchange — compute leads,
    the exchange trails, so chunk ``k``'s inverse FFT overlaps chunk
    ``k-1``'s wire time."""
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32, pipeline=2)
    uh = plan.allocate_output()
    jpr = jax.make_jaxpr(
        lambda d: plan.backward(PencilArray(plan.output_pencil, d)).data
    )(uh.data).jaxpr

    checked = 0
    for sj in _subjaxprs(jpr):
        eqns = list(sj.eqns)
        t_idx = [i for i, e in enumerate(eqns)
                 if e.primitive.name == "all_to_all"]
        f_idx = [i for i, e in enumerate(eqns) if _contains_fft(e)]
        if len(t_idx) < 2 or not f_idx:
            continue
        checked += 1
        deps = _eqn_deps(eqns)
        for f in f_idx:
            for t in t_idx:
                assert t not in deps[f], (
                    "inverse transform depends on an exchange — the "
                    "mirrored fused hop reintroduced the barrier")
    assert checked >= 1, "no fused hop body found in the jaxpr"


def test_transposition_object_overlap_api(topo):
    """Eager overlap pattern, reference-API parity: start the transpose
    (async dispatch), do unrelated work, then consume — waitall() is the
    no-op the compiler made of MPI.Waitall."""
    pen_x = Pencil(topo, (12, 10, 8), (1, 2))
    pen_y = Pencil(topo, (12, 10, 8), (0, 2))
    u = np.random.default_rng(0).standard_normal((12, 10, 8))
    x = PencilArray.from_global(pen_x, u)

    t = Transposition(pen_y, x)
    y = t.execute()          # dispatches; JAX execution is async
    other = jnp.ones((32, 32)) @ jnp.ones((32, 32))  # overlapped work
    t.waitall()              # no-op parity shim
    from pencilarrays_tpu import gather

    np.testing.assert_allclose(gather(y), u, rtol=1e-12)
    assert float(other[0, 0]) == 32.0


def test_pipelined_wire_packs_per_chunk(topo):
    """ISSUE 13 satellite: ``Pipelined(chunks=K)`` + ``wire_dtype``
    compose PER CHUNK — the cast-pack sits inside each chunk's program
    (one 16-bit pack per exchange, chunk-sized), never as one fused
    full-array materialization that would serialize the chunks and
    kill the overlap win.  Pinned on the jaxpr: every chunk exchange
    moves the packed u16 payload, every pack output is exactly its
    chunk's operand shape, and no exchange gained a dependency on any
    FFT stage."""
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32, pipeline=2,
                         wire_dtype="bf16")
    assert any(s[0] == "ft" for s in plan._steps), "no hop fused"
    x = plan.allocate_input()
    jpr = jax.make_jaxpr(
        lambda d: plan.forward(PencilArray(plan.input_pencil, d)).data
    )(x.data).jaxpr

    checked = 0
    for sj in _subjaxprs(jpr):
        eqns = list(sj.eqns)
        t_idx = [i for i, e in enumerate(eqns)
                 if e.primitive.name == "all_to_all"]
        f_idx = [i for i, e in enumerate(eqns) if _contains_fft(e)]
        if len(t_idx) < 2 or not f_idx:
            continue  # not a fused hop body
        checked += 1
        # every chunk's exchange moves the PACKED 16-bit wire payload
        a2a_elems = []
        for t in t_idx:
            aval = eqns[t].invars[0].aval
            assert str(aval.dtype) == "uint16", (
                "fused chunk exchange is not the packed wire payload")
            a2a_elems.append(int(np.prod(aval.shape)))
        # one pack per chunk, each chunk-sized — a single full-array
        # pack (== sum of the chunks) would be the fused
        # materialization the satellite forbids
        packs = [e for e in eqns
                 if e.primitive.name == "bitcast_convert_type"
                 and str(e.outvars[0].aval.dtype) == "uint16"]
        assert len(packs) == len(t_idx)
        full_block = sum(a2a_elems)
        for e in packs:
            n = int(np.prod(e.outvars[0].aval.shape))
            assert n in a2a_elems and n < full_block
        # the overlap precondition survives the wire: no exchange
        # (pack included, it feeds the exchange) waits on any FFT
        deps = _eqn_deps(eqns)
        for t in t_idx:
            for f in f_idx:
                assert f not in deps[t], (
                    "wire pack reintroduced the hop->transform barrier")
    assert checked >= 1, "no fused hop body found in the jaxpr"
