"""Comm-compute overlap — the XLA re-specification of the reference's
``waitall=false`` + ``MPI.Waitany`` unpack pipeline
(``Transpositions.jl:142-158, 510-516``).

On TPU, overlap is owned by XLA's latency-hiding scheduler: collectives
lower to async ``-start``/``-done`` pairs and independent compute is
scheduled between them.  That rewrite happens in the TPU backend (the CPU
backend lowers collectives synchronously), so what these tests pin is the
property the scheduler NEEDS and that this library controls: a transpose
and unrelated compute placed in one jitted program are **data-dependency
free** — nothing in the traced program sequences the exchange against the
independent work, so the scheduler is free to overlap them.  Checked on
the jaxpr (the dependency graph XLA receives).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll, Pencil, PencilArray, Ring, Topology, Transposition, transpose,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def _eqn_deps(eqns):
    """Map eqn index -> set of eqn indices it transitively depends on."""
    producer = {}
    for j, e in enumerate(eqns):
        for v in e.outvars:
            producer[v] = j
    deps = []
    for e in eqns:
        seen = set()
        stack = [v for v in e.invars if type(v).__name__ != "Literal"]
        while stack:
            v = stack.pop()
            j = producer.get(v)
            if j is not None and j not in seen:
                seen.add(j)
                stack.extend(u for u in eqns[j].invars
                             if type(u).__name__ != "Literal")
        deps.append(seen)
    return deps


@pytest.mark.parametrize("method", [AllToAll(), Ring()])
def test_transpose_and_independent_compute_are_dependency_free(topo, method):
    """The scheduler's overlap precondition: in one traced program, the
    exchange neither depends on nor is depended on by the unrelated
    matmul."""
    pen_x = Pencil(topo, (16, 16, 16), (1, 2))
    pen_y = Pencil(topo, (16, 16, 16), (0, 2))
    x = PencilArray.zeros(pen_x)
    w = jnp.ones((64, 64))

    def f(d, m):
        y = transpose(PencilArray(pen_x, d), pen_y, method=method)
        z = m @ m  # independent work the scheduler may overlap
        return y.data, z

    jpr = jax.make_jaxpr(f)(x.data, w).jaxpr
    eqns = jpr.eqns
    t_idx = [i for i, e in enumerate(eqns)
             if "all_to_all" in str(e) or "ppermute" in str(e)]
    d_idx = [i for i, e in enumerate(eqns) if "dot_general" in str(e)
             and "all_to_all" not in str(e) and "ppermute" not in str(e)]
    assert t_idx and d_idx, (len(t_idx), len(d_idx))
    deps = _eqn_deps(eqns)
    for t in t_idx:
        for d in d_idx:
            assert t not in deps[d], "matmul depends on the exchange"
            assert d not in deps[t], "exchange depends on the matmul"

    # and both compile into ONE module (one dispatch, one schedule)
    hlo = jax.jit(f).lower(x.data, w).compile().as_text()
    assert "dot(" in hlo or "dot-general" in hlo


def test_transposition_object_overlap_api(topo):
    """Eager overlap pattern, reference-API parity: start the transpose
    (async dispatch), do unrelated work, then consume — waitall() is the
    no-op the compiler made of MPI.Waitall."""
    pen_x = Pencil(topo, (12, 10, 8), (1, 2))
    pen_y = Pencil(topo, (12, 10, 8), (0, 2))
    u = np.random.default_rng(0).standard_normal((12, 10, 8))
    x = PencilArray.from_global(pen_x, u)

    t = Transposition(pen_y, x)
    y = t.execute()          # dispatches; JAX execution is async
    other = jnp.ones((32, 32)) @ jnp.ones((32, 32))  # overlapped work
    t.waitall()              # no-op parity shim
    from pencilarrays_tpu import gather

    np.testing.assert_allclose(gather(y), u, rtol=1e-12)
    assert float(other[0, 0]) == 32.0
