"""Pallas kernel tests — the VMEM-tiled permute must be bit-identical to
``jnp.transpose`` and plug into the transpose engine transparently (via
interpret mode on the CPU test mesh)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Permutation, Topology, gather, transpose
from pencilarrays_tpu.ops.pallas_kernels import (
    pallas_enabled,
    pallas_permute,
    supported,
)


def test_supported_predicate():
    """Gated to the measured near-parity class (PALLAS_SWEEP.json):
    3-D f32/i32, output leading dim = input minor dim."""
    assert supported((256, 128, 256), (2, 0, 1), jnp.float32)
    assert not supported((250, 256, 256), (2, 0, 1), jnp.float32)  # ragged
    # perf size gate is a TPU bandwidth criterion: CPU interpret path
    # (virtual-mesh tests) accepts small shapes
    assert not supported((128, 128, 128), (2, 0, 1), jnp.float32, "tpu")
    assert supported((128, 128, 128), (2, 0, 1), jnp.float32, "cpu")
    assert not supported((256, 128, 256), (2, 0, 1), jnp.float64)  # dtype
    assert not supported((8,), (0,), jnp.float32)  # rank
    # measured-regression classes are rejected so opt-in is never a trap
    assert not supported((256, 128), (1, 0), jnp.bfloat16)     # bf16 0.5x
    assert not supported((256, 256, 256), (2, 1, 0), jnp.float32)  # unmeasured
    assert not supported((256, 256, 256), (1, 2, 0), jnp.float32)  # 0.19x
    assert not supported((128, 128, 128, 8), (1, 2, 0, 3),
                         jnp.float32)                          # 4-D 0.03x


@pytest.mark.parametrize(
    "shape,axes",
    [
        ((256, 128, 256), (2, 0, 1)),
        ((128, 256, 128), (1, 0, 2)),
        ((128, 128, 128), (2, 1, 0)),
        ((256, 128), (1, 0)),
        ((64, 8, 128, 128), (3, 2, 0, 1)),
    ],
)
def test_permute_matches_numpy(shape, axes):
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal(shape), jnp.float32)
    y = pallas_permute(x, axes, interpret=True)
    np.testing.assert_array_equal(np.asarray(y),
                                  np.transpose(np.asarray(x), axes))


def test_engine_integration_bit_identical(devices, monkeypatch):
    """With the pallas path enabled, engine results must not change by a
    single bit (pure data movement)."""
    from pencilarrays_tpu.ops import pallas_kernels

    topo = Topology((2, 4))
    shape = (128, 128, 128)  # tile-friendly local blocks
    u = np.random.default_rng(1).standard_normal(shape).astype(np.float32)
    pen_a = Pencil(topo, shape, (1, 2), permutation=Permutation(1, 2, 0))
    pen_b = pen_a.replace(permutation=Permutation(2, 0, 1))
    pen_c = Pencil(topo, shape, (0, 2), permutation=Permutation(2, 0, 1))
    x = PencilArray.from_global(pen_a, u)
    ref_local = transpose(x, pen_b)
    ref_a2a = transpose(x, pen_c)
    monkeypatch.setenv("PENCILARRAYS_TPU_PALLAS", "1")
    assert pallas_kernels.pallas_enabled()
    got_local = transpose(x, pen_b)
    got_a2a = transpose(x, pen_c)
    assert bool((got_local.data == ref_local.data).all())
    assert bool((got_a2a.data == ref_a2a.data).all())
    np.testing.assert_array_equal(gather(got_local), u)


def test_engine_fallback_on_ragged(devices, monkeypatch):
    """Unsupported (ragged) shapes silently use the XLA path."""
    monkeypatch.setenv("PENCILARRAYS_TPU_PALLAS", "1")
    topo = Topology((2, 4))
    shape = (42, 31, 29)
    u = np.random.default_rng(2).standard_normal(shape)
    pen_a = Pencil(topo, shape, (1, 2))
    pen_b = Pencil(topo, shape, (0, 2), permutation=Permutation(1, 0, 2))
    x = PencilArray.from_global(pen_a, u)
    np.testing.assert_array_equal(gather(transpose(x, pen_b)), u)
