"""Partition-tolerant control plane (ISSUE 20): CAS + fencing on the
KV wire, the quorum gate on membership consensus, and the durable
router WAL with exactly-once replay.

The contracts under test:

* **CAS** — ``set_if`` publishes iff the current value matches;
  exactly one of N concurrent swappers wins (FileKV's lock-file
  serialization is genuinely atomic on one filesystem);
* **fencing** — a write through :class:`FencedKV` whose ``(gen,
  epoch)`` token is behind the published fence is rejected typed
  (``FencedWriteError``) before touching the store, journaled
  (``cluster.fence``) and counted; the fence advance is monotonic and
  race-safe;
* **quorum** — a minority-side rank cannot form generation N+1: it
  exits typed ``QuorumLossError`` naming ``have``/``need``/``of``;
  the majority side reforms with the dead peer counted out of the
  denominator on fresh evidence; ``PENCILARRAYS_TPU_ELASTIC_QUORUM=
  off`` turns the gate into a loud (RuntimeWarning + journaled
  ``bypass``) no-op;
* **WAL** — CRC framing rejects torn tails; ``replay`` is a pure
  idempotent fold that dedups completions; rotation preserves record
  order; a restarted router replays the log and resolves every
  admitted ticket exactly once — from the published result when one
  exists (zero re-execution), via re-bind otherwise, and a deadline
  that lapsed while the router sat dead fails typed;
* **durability** — FileKV fsyncs every newly created ancestor
  directory in its parent (the crash-after-publish hole);
* **lint** — the ``kv-fenced`` rule flags raw KV writes in
  ``cluster/``/``fleet/`` unless fenced or inline-justified.
"""

import json
import os
import textwrap
import threading
import time

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import cluster, guard, obs
from pencilarrays_tpu.analysis.lint import lint_tree
from pencilarrays_tpu.cluster import (FencedWriteError, QuorumLossError,
                                      elastic)
from pencilarrays_tpu.cluster.consensus import Coordinator
from pencilarrays_tpu.cluster.errors import (ConsensusTimeoutError,
                                             ReformError)
from pencilarrays_tpu.cluster.kv import FencedKV, FileKV
from pencilarrays_tpu.fleet import FleetRouter, MeshWorker
from pencilarrays_tpu.fleet import wire
from pencilarrays_tpu.fleet import wal as walmod
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.resilience import faults
from pencilarrays_tpu.serve import SLO, DeadlineError, PlanService


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Every test starts with cluster/guard/obs disabled, faults
    cleared, epoch 0 (the test_cluster discipline)."""
    for var in (cluster.ENV_VAR, cluster.RANK_VAR, cluster.WORLD_VAR,
                cluster.LEASE_TTL_VAR, cluster.VERDICT_TIMEOUT_VAR,
                guard.ENV_VAR, obs.ENV_VAR, faults.ENV_VAR,
                elastic.ENV_VAR, elastic.TIMEOUT_VAR,
                elastic.MIN_WORLD_VAR, elastic.QUORUM_VAR,
                "PENCILARRAYS_TPU_FLEET_WAL_MAX_MB"):
        monkeypatch.delenv(var, raising=False)
    cluster._reset_for_tests()
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    cluster._reset_for_tests()
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _run_ranks(*thunks):
    """One callable per rank on its own thread; re-raises the first
    failure, returns rank->result."""
    results, errors = {}, {}

    def wrap(r, fn):
        try:
            results[r] = fn()
        except BaseException as e:   # noqa: BLE001 - re-raised below
            errors[r] = e

    threads = [threading.Thread(target=wrap, args=(r, fn))
               for r, fn in enumerate(thunks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        raise errors[min(errors)]
    return results


# ---------------------------------------------------------------------------
# CAS: set_if
# ---------------------------------------------------------------------------

def test_set_if_create_swap_reject(tmp_path):
    kv = FileKV(str(tmp_path))
    # expected=None: create iff absent
    assert kv.set_if("ns/fence", "v1", None) is True
    assert kv.try_get("ns/fence") == "v1"
    assert kv.set_if("ns/fence", "v1b", None) is False   # already exists
    # wrong expectation loses; right expectation swaps
    assert kv.set_if("ns/fence", "v2", "stale") is False
    assert kv.try_get("ns/fence") == "v1"
    assert kv.set_if("ns/fence", "v2", "v1") is True
    assert kv.try_get("ns/fence") == "v2"


def test_set_if_exactly_one_concurrent_winner(tmp_path):
    kv = FileKV(str(tmp_path))
    wins = []

    def racer(i):
        if kv.set_if("race/key", f"winner-{i}", None):
            wins.append(i)

    threads = [threading.Thread(target=racer, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert len(wins) == 1
    assert kv.try_get("race/key") == f"winner-{wins[0]}"
    # no CAS scaffolding survives the race
    assert not os.path.exists(os.path.join(str(tmp_path), "race",
                                           "key.lock"))


def test_set_if_broken_lock_is_recovered(tmp_path, monkeypatch):
    """A writer that died inside the critical section leaves the lock
    file behind; the next swapper breaks it after the timeout instead
    of wedging forever."""
    monkeypatch.setattr(FileKV, "CAS_LOCK_TIMEOUT_S", 0.2)
    kv = FileKV(str(tmp_path))
    kv.set("a/k", "v0")
    lock = os.path.join(str(tmp_path), "a", "k.lock")
    with open(lock, "w"):
        pass                         # the crashed holder's wreckage
    t0 = time.monotonic()
    assert kv.set_if("a/k", "v1", "v0") is True
    assert time.monotonic() - t0 >= 0.15
    assert kv.try_get("a/k") == "v1"


# ---------------------------------------------------------------------------
# the kv.get / kv.set fault points
# ---------------------------------------------------------------------------

def test_kv_partition_mode_is_typed_and_total(tmp_path):
    kv = FileKV(str(tmp_path))
    kv.set("pre/r0", "there")
    with faults.active("kv.set:partition"):
        with pytest.raises(ConsensusTimeoutError):
            kv.set("pre/r1", "x")
        with pytest.raises(ConsensusTimeoutError):
            kv.set_if("pre/r1", "x", None)
        with pytest.raises(ConsensusTimeoutError):
            kv.delete("pre/r0")
    assert kv.try_get("pre/r0") == "there"   # delete never reached it
    with faults.active("kv.get:partition"):
        # an existing key is unreadable under the partition, and the
        # blocking wait runs out into the same typed timeout a real
        # partition produces
        assert kv.try_get("pre/r0") is None
        with pytest.raises(ConsensusTimeoutError):
            kv.get("pre/r0", 0.2)
    assert kv.try_get("pre/r0") == "there"   # heals when it lifts


def test_kv_drop_mode_loses_silently(tmp_path):
    kv = FileKV(str(tmp_path))
    with faults.active("kv.set:drop*2"):
        kv.set("a/r0", "lost")               # acked, never stored
        assert kv.set_if("a/r0", "lost2", None) is True   # "swapped"
    assert kv.try_get("a/r0") is None
    kv.set("a/r0", "kept")
    with faults.active("kv.get:drop"):
        assert kv.try_get("a/r0") is None    # the dropped read misses
    assert kv.try_get("a/r0") == "kept"


# ---------------------------------------------------------------------------
# FileKV durability: new ancestor dirs are fsync'd
# ---------------------------------------------------------------------------

def test_new_ancestor_dirs_fsynced_topdown(tmp_path, monkeypatch):
    """The atomic publish fsyncs the file's directory entry; the
    regression here is the *directory chain* — every newly created
    ancestor must be fsync'd in ITS parent (top-down), or a crash can
    unlink the chain and take the published-looking key with it."""
    from pencilarrays_tpu.cluster import kv as kvmod

    synced = []
    monkeypatch.setattr(kvmod, "fsync_dir",
                        lambda d: synced.append(os.path.normpath(d)))
    kv = FileKV(str(tmp_path / "root"))
    kv.set("a/b/c/r0", "v")
    root = os.path.normpath(str(tmp_path / "root"))
    assert synced == [root,
                      os.path.join(root, "a"),
                      os.path.join(root, "a", "b")]
    # an existing chain re-syncs nothing
    synced.clear()
    kv.set("a/b/c/r1", "v")
    assert synced == []


# ---------------------------------------------------------------------------
# FencedKV: the zombie write guard
# ---------------------------------------------------------------------------

def test_fenced_write_rejected_behind_fence(tmp_path):
    obs.enable(str(tmp_path / "obs"))
    try:
        kv = FileKV(str(tmp_path / "kv"))
        zombie = FencedKV(kv, namespace="pa", generation=0, epoch=0)
        # pre-fencing default: no published fence, every token passes
        zombie.set("pa/state/r0", "v0")
        assert zombie.try_get("pa/state/r0") == "v0"
        # the live mesh reforms and advances the fence past the zombie
        live = FencedKV(kv, namespace="pa", generation=0, epoch=0)
        assert live.advance(1, 1) == (1, 1)
        assert live.token() == (1, 1)        # the advancer is a member
        live.set("pa/state/r0", "v1")        # current token writes fine
        for op in (lambda: zombie.set("pa/state/r0", "evil"),
                   lambda: zombie.set_if("pa/state/r0", "evil", "v1"),
                   lambda: zombie.delete("pa/state/r0")):
            with pytest.raises(FencedWriteError) as ei:
                op()
            assert ei.value.token == (0, 0)
            assert ei.value.fence == (1, 1)
        # reads pass through unchecked; nothing the zombie did landed
        assert zombie.try_get("pa/state/r0") == "v1"
    finally:
        obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    fences = [e for e in events if e["ev"] == "cluster.fence"]
    assert len(fences) == 3
    assert all(e["gen"] == 0 and e["fence_gen"] == 1 for e in fences)
    counters = obs_metrics.registry.snapshot()["counters"]
    assert counters["cluster.fenced_writes"] == 3


def test_fence_advance_is_monotonic(tmp_path):
    kv = FileKV(str(tmp_path))
    a = FencedKV(kv, namespace="pa")
    assert a.advance(3, 1) == (3, 1)
    # a lagging advance adopts the higher fence instead of regressing
    b = FencedKV(kv, namespace="pa")
    assert b.advance(2, 9) == (3, 1)
    assert b.token() == (3, 1)
    # epoch advances within a generation; generation outranks epoch
    assert a.advance(3, 2) == (3, 2)
    assert a.advance(4, 0) == (4, 0)
    assert (3, 9) < (4, 0)                   # the lexicographic order


def test_fence_advance_concurrent_race_converges(tmp_path):
    kv = FileKV(str(tmp_path))
    results = _run_ranks(
        *[lambda g=g: FencedKV(kv, namespace="pa").advance(g, 0)
          for g in range(1, 7)])
    # every racer lands on a fence >= its own bid, and the store holds
    # the maximum bid (no lost update, no regression)
    for g, got in results.items():
        assert got >= (g + 1, 0)
    final = json.loads(kv.try_get("pa/fence"))
    assert (final["gen"], final["epoch"]) == (6, 0)


# ---------------------------------------------------------------------------
# the quorum gate
# ---------------------------------------------------------------------------

def test_quorum_minority_exits_typed(tmp_path):
    """Rank 0 is cut off from peers that are alive and heartbeating
    (their leases stay fresh — no evidence they left).  Its membership
    round assembles only its own vote: 1 of 3 is below strict
    majority, so it must NOT form a rival mesh — typed exit."""
    obs.enable(str(tmp_path / "obs"))
    kv = FileKV(str(tmp_path / "kv"))
    coords = {r: Coordinator(kv, r, 3, lease_ttl=5.0,
                             verdict_timeout=20)
              for r in range(3)}
    try:
        with pytest.raises(QuorumLossError) as ei:
            elastic.agree_membership(coords[0], timeout=0.4,
                                     max_rounds=2)
        assert isinstance(ei.value, ReformError)   # still a reform error
        assert ei.value.have == (0,)
        assert ei.value.need == 2
        assert ei.value.of == (0, 1, 2)
    finally:
        for c in coords.values():
            c.shutdown()
        obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    quorums = [e for e in events if e["ev"] == "cluster.quorum"]
    assert quorums and quorums[-1]["verdict"] == "fail"
    assert quorums[-1]["have"] == [0]
    assert quorums[-1]["gone"] == []     # fresh leases: nobody is gone


def test_quorum_majority_reforms_over_dead_peer(tmp_path):
    """The flip side: rank 2's lease went stale (fresh evidence it is
    gone), so the denominator shrinks to [0, 1] and the surviving pair
    IS a strict majority — membership agrees, quorum journaled as a
    pass on both ranks."""
    obs.enable(str(tmp_path / "obs"))
    kv = FileKV(str(tmp_path / "kv"))
    coords = {r: Coordinator(kv, r, 3, lease_ttl=0.4,
                             verdict_timeout=20)
              for r in range(3)}
    coords[2].shutdown()                 # crash: renewals stop
    time.sleep(0.9)                      # the lease goes stale
    try:
        res = _run_ranks(
            lambda: elastic.agree_membership(coords[0], timeout=20,
                                             reason="peer-failure"),
            lambda: elastic.agree_membership(coords[1], timeout=20,
                                             reason="peer-failure"))
        assert res[0].members == res[1].members == [0, 1]
        assert res[0].gen == res[1].gen
    finally:
        for r in (0, 1):
            coords[r].shutdown()
        obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    quorums = [e for e in events if e["ev"] == "cluster.quorum"]
    assert {e["rank"] for e in quorums} == {0, 1}
    for e in quorums:
        assert e["verdict"] == "pass"
        assert e["of"] == [0, 1] and e["need"] == 2
        assert e["gone"] == [2]


def test_quorum_escape_hatch_is_loud(tmp_path, monkeypatch):
    """PENCILARRAYS_TPU_ELASTIC_QUORUM=off: the same minority round
    proceeds — but with a RuntimeWarning and a journaled ``bypass``
    verdict, never silently.  (The round budget then runs out against
    the silent peers: a ReformError, not a QuorumLossError.)"""
    monkeypatch.setenv(elastic.QUORUM_VAR, "off")
    obs.enable(str(tmp_path / "obs"))
    kv = FileKV(str(tmp_path / "kv"))
    coords = {r: Coordinator(kv, r, 3, lease_ttl=5.0,
                             verdict_timeout=20)
              for r in range(3)}
    try:
        with pytest.warns(RuntimeWarning, match="split-brain"):
            with pytest.raises(ReformError) as ei:
                elastic.agree_membership(coords[0], timeout=0.3,
                                         max_rounds=1)
        assert not isinstance(ei.value, QuorumLossError)
    finally:
        for c in coords.values():
            c.shutdown()
        obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    quorums = [e for e in events if e["ev"] == "cluster.quorum"]
    assert quorums and quorums[-1]["verdict"] == "bypass"


# ---------------------------------------------------------------------------
# the router WAL: framing, replay, rotation
# ---------------------------------------------------------------------------

def _append_all(wal_dir, records, **kw):
    w = walmod.RouterWAL(str(wal_dir), **kw)
    for rec in records:
        w.append(rec)
    w.close()


def test_wal_roundtrip_and_torn_tail(tmp_path):
    recs = [{"op": "admit", "tid": "t1", "req": {"x": 1}},
            {"op": "place", "tid": "t1", "mesh": 0, "rebinds": 0},
            {"op": "complete", "tid": "t1", "outcome": "ok"}]
    _append_all(tmp_path, recs)
    got, skipped = walmod.read_wal(str(tmp_path))
    assert got == recs and skipped == 0
    # a SIGKILL mid-append leaves a torn tail: its CRC cannot match,
    # so replay skips (and counts) it instead of trusting what parses
    with open(os.path.join(str(tmp_path), walmod.ACTIVE), "a") as f:
        f.write(walmod._frame({"op": "admit", "tid": "t2",
                               "req": {}})[:20])
    got, skipped = walmod.read_wal(str(tmp_path))
    assert got == recs and skipped == 1
    # foreign wreckage (plausible JSON, no frame) is skipped too
    with open(os.path.join(str(tmp_path), walmod.ACTIVE), "a") as f:
        f.write('\n{"op": "complete", "tid": "t1", "outcome": "ok"}\n')
    got, skipped = walmod.read_wal(str(tmp_path))
    assert got == recs and skipped == 2


def test_wal_replay_fold_semantics():
    recs = [
        {"op": "admit", "tid": "a", "req": "RA"},
        {"op": "place", "tid": "a", "mesh": 0, "rebinds": 0},
        {"op": "admit", "tid": "b", "req": "RB"},
        {"op": "place", "tid": "b", "mesh": 1, "rebinds": 0},
        {"op": "place", "tid": "b", "mesh": 2, "rebinds": 1},  # rebind
        {"op": "complete", "tid": "b", "outcome": "ok"},
        {"op": "complete", "tid": "b", "outcome": "ok"},  # dup: 2 meshes
        {"op": "complete", "tid": "c", "outcome": "ok"},  # admit torn off
        {"op": "admit", "tid": "c", "req": "RC"},         # late re-admit
        {"op": "place", "tid": "zzz", "mesh": 0},         # orphan place
    ]
    st = walmod.replay(recs)
    # only the genuinely unresolved ticket survives, with its last
    # binding and its rebind budget consumption intact
    assert set(st["pending"]) == {"a"}
    assert st["pending"]["a"] == {"req": "RA", "mesh": 0, "rebinds": 0}
    # a complete for a tid whose admit sat in the torn tail still
    # resolves — the ticket provably finished, never resurrect it
    assert st["resolved"] == {"b", "c"}
    assert st["duplicates"] == 1
    # pure fold: replaying a replayed log is the same state
    assert walmod.replay(recs) == st


def test_wal_rotation_preserves_order(tmp_path):
    recs = [{"op": "place", "tid": f"t{i:03d}", "mesh": 0,
             "rebinds": 0} for i in range(20)]
    _append_all(tmp_path, recs, max_bytes=200)
    segments = [n for n in os.listdir(str(tmp_path))
                if walmod._SEGMENT_RE.match(n)]
    assert len(segments) >= 2            # the cap actually rotated
    got, skipped = walmod.read_wal(str(tmp_path))
    assert got == recs and skipped == 0  # append order, across segments


def test_wal_rotation_cap_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PENCILARRAYS_TPU_FLEET_WAL_MAX_MB", "0.0001")
    recs = [{"op": "place", "tid": f"t{i:03d}", "mesh": 0,
             "rebinds": 0} for i in range(8)]
    _append_all(tmp_path, recs)          # late-armed env cap (~105 B)
    assert any(walmod._SEGMENT_RE.match(n)
               for n in os.listdir(str(tmp_path)))
    got, _ = walmod.read_wal(str(tmp_path))
    assert got == recs


# ---------------------------------------------------------------------------
# router recovery: exactly-once across router incarnations
# ---------------------------------------------------------------------------

def _kv(tmp_path, sub="kv"):
    return FileKV(os.path.join(str(tmp_path), sub))


def _service(devices, shape=(8, 6, 4), name="fft"):
    topo = pa.Topology((1,), devices=devices[:1])
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    svc.register_plan(name, lambda ctx: PencilFFTPlan(topo, shape))
    return svc


def _worker(kv, mesh, devices, *, ttl=5.0):
    w = MeshWorker(kv, mesh, service=_service(devices), ttl=ttl)
    w.prewarm(["fft"])
    return w


def _host(seed, shape=(8, 6, 4)):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


@pytest.mark.usefixtures("devices")
def test_router_death_after_results_resolves_without_reexecution(
        tmp_path, devices):
    """The router is killed AFTER the mesh published both results but
    BEFORE it harvested them.  The restarted router replays the WAL,
    re-parks both tickets, and resolves each from the result already
    on the wire — zero re-binds, zero re-executions, zero
    duplicates."""
    obs.enable(str(tmp_path / "obs"))
    kv = _kv(tmp_path)
    wal_dir = str(tmp_path / "wal")
    worker = _worker(kv, 0, devices)
    worker.start()
    r2 = None
    try:
        r1 = FleetRouter(kv, ttl=5.0, wal_dir=wal_dir)
        r1.register_mesh(0)
        r1.submit("acme", _host(0), name="fft")
        r1.submit("acme", _host(1), name="fft")
        # write-AHEAD: both admissions hit the platter before the wire
        recs, _ = walmod.read_wal(wal_dir)
        assert [r["op"] for r in recs] == ["admit", "place"] * 2
        assert worker.step() == 2        # results published on the wire
        # "SIGKILL": r1 is abandoned un-pumped — its in-memory pending
        # map dies with it, the WAL is all that survives
        r1._wal.close()
        del r1
        r2 = FleetRouter(kv, ttl=5.0, wal_dir=wal_dir)
        r2.register_mesh(0)
        rep = r2.recover()
        assert rep["outcome"] == "clean"
        assert rep["reparked"] == 2 and rep["resolved"] == 0
        assert r2.drain(5.0) == 0
        st = r2.stats()
        assert st["completed"] == 2 and st["duplicates"] == 0
        assert st["rebound"] == 0        # resolved from results: no
        assert st["failed"] == 0         # re-publish, no re-execution
        # the wire is empty and the rebind budget untouched
        assert kv.list_dir(wire.req_dir("pa", 0)) == {}
        # replay-after-replay: the completes r2 logged make the whole
        # WAL resolved — nothing re-parks
        rep2 = r2.recover()
        assert rep2["reparked"] == 0 and rep2["resolved"] == 2
    finally:
        worker.close()
        if r2 is not None:
            r2.close()
        obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    assert obs.lint_journal(events) == []
    wals = [e for e in events if e["ev"] == "fleet.wal"]
    assert [w["reparked"] for w in wals] == [2, 0]
    counters = obs_metrics.registry.snapshot()["counters"]
    assert counters["fleet.wal_replays{outcome=clean}"] == 2


@pytest.mark.usefixtures("devices")
def test_router_death_before_execution_rebinds_and_resolves(
        tmp_path, devices):
    """The router dies BEFORE the mesh saw either request (admitted,
    placed, never executed).  Recovery re-parks both; the next pump
    re-publishes them (consuming one rebind each — the budget spans
    router lives) and the drained results are numerically correct."""
    kv = _kv(tmp_path)
    wal_dir = str(tmp_path / "wal")
    worker = _worker(kv, 0, devices)
    worker.start()
    r2 = None
    try:
        r1 = FleetRouter(kv, ttl=5.0, wal_dir=wal_dir)
        r1.register_mesh(0)
        u = _host(7)
        r1.submit("acme", u, name="fft")
        # the mesh never stepped: wipe the wire copy to model requests
        # lost with the old router's final un-synced kv batch
        r1._wal.close()
        del r1
        r2 = FleetRouter(kv, ttl=5.0, wal_dir=wal_dir)
        r2.register_mesh(0)
        rep = r2.recover()
        assert rep["reparked"] == 1
        r2.pump()                        # re-bind: republish to mesh 0
        assert r2.stats()["rebound"] == 1
        assert worker.step() == 1        # NOW it executes
        assert r2.drain(5.0) == 0
        st = r2.stats()
        assert st["completed"] == 1 and st["duplicates"] == 0
        # the recovered payload crossed the wire bit-identical: the
        # mesh computed the right transform from the WAL's verbatim
        # wire blob
        recs, _ = walmod.read_wal(wal_dir)
        req = next(r["req"] for r in recs if r["op"] == "admit")
        np.testing.assert_array_equal(
            wire.decode_request(req)["payload"], u)
    finally:
        worker.close()
        if r2 is not None:
            r2.close()


@pytest.mark.usefixtures("devices")
def test_recovered_deadline_lapses_typed(tmp_path, devices):
    """A deadline that ran out while the router sat dead fails typed
    at the first recovered pump — death never silently extends an SLO
    budget."""
    kv = _kv(tmp_path)
    wal_dir = str(tmp_path / "wal")
    worker = _worker(kv, 0, devices)
    worker.start()
    r2 = None
    try:
        slos = {"whale": SLO(deadline_s=0.15)}
        r1 = FleetRouter(kv, ttl=5.0, slos=slos, wal_dir=wal_dir)
        r1.register_mesh(0)
        r1.submit("whale", _host(3), name="fft")
        r1._wal.close()
        del r1                           # dead before anything ran
        # model the mesh never answering: drop the wire copy so the
        # recovered ticket cannot resolve from a result
        for k in list(kv.list_dir(wire.req_dir("pa", 0))):
            kv.delete(k)
        time.sleep(0.25)                 # the budget lapses meanwhile
        r2 = FleetRouter(kv, ttl=5.0, slos=slos, wal_dir=wal_dir)
        r2.register_mesh(0)
        assert r2.recover()["reparked"] == 1
        r2.pump()
        st = r2.stats()
        assert st["expired"] == 1 and st["failed"] == 1
        assert st["completed"] == 0 and st["pending"] == 0
        # the lapse is on the WAL record for the NEXT incarnation
        recs, _ = walmod.read_wal(wal_dir)
        final = [r for r in recs if r["op"] == "complete"]
        assert [r["outcome"] for r in final] == ["DeadlineError"]
    finally:
        worker.close()
        if r2 is not None:
            r2.close()


@pytest.mark.usefixtures("devices")
def test_recovery_with_torn_tail_still_resolves_committed(
        tmp_path, devices):
    """A torn final record (the append the SIGKILL interrupted) is
    skipped and counted — recovery reports ``torn-tail`` and every
    COMMITTED admission still resolves exactly once."""
    kv = _kv(tmp_path)
    wal_dir = str(tmp_path / "wal")
    worker = _worker(kv, 0, devices)
    worker.start()
    r2 = None
    try:
        r1 = FleetRouter(kv, ttl=5.0, wal_dir=wal_dir)
        r1.register_mesh(0)
        r1.submit("acme", _host(4), name="fft")
        worker.step()
        r1._wal.close()
        del r1
        with open(os.path.join(wal_dir, walmod.ACTIVE), "a") as f:
            f.write(walmod._frame({"op": "admit", "tid": "torn",
                                   "req": "x" * 64})[:30])
        r2 = FleetRouter(kv, ttl=5.0, wal_dir=wal_dir)
        r2.register_mesh(0)
        rep = r2.recover()
        assert rep["outcome"] == "torn-tail"
        assert rep["skipped"] == 1 and rep["reparked"] == 1
        assert r2.drain(5.0) == 0
        assert r2.stats()["completed"] == 1
    finally:
        worker.close()
        if r2 is not None:
            r2.close()


# ---------------------------------------------------------------------------
# the kv-fenced lint rule
# ---------------------------------------------------------------------------

def _write(root, rel, content):
    path = os.path.join(root, *rel.split("/"))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(content))


def _kv_lint_fixture(tmp_path, cluster_src, outside_src=""):
    root = str(tmp_path / "repo")
    _write(root, "pencilarrays_tpu/obs/schema.py", """
        EVENT_TYPES = {"hop": ("method",)}
        """)
    _write(root, "pencilarrays_tpu/resilience/faults.py", """
        POINTS = frozenset({"io.open"})
        """)
    _write(root, "docs/Resilience.md", "| `io.open` |")
    _write(root, "README.md", "docs")
    _write(root, "pencilarrays_tpu/cluster/health.py", cluster_src)
    if outside_src:
        _write(root, "pencilarrays_tpu/serve/thing.py", outside_src)
    return root


def test_lint_kv_fenced_rules(tmp_path):
    root = _kv_lint_fixture(tmp_path, """
        def renew(self, kv):
            kv.set("lease/r0", "t")                    # raw: flagged
            kv.delete("lease/r0")   # kv-unfenced: GC of my own key
            self.fenced.set("lease/r0", "t")           # sanctioned
            kv.set("lease/r1", "t")  # kv-unfenced:
            # kv-unfenced: the block-above form of the excuse
            kv.set_if("fence", "v", None)
            board.publish("x")                         # not a KV write
        """, outside_src="""
        def g(kv):
            kv.set("free/r0", "x")      # serve/ is out of scope
        """)
    found = sorted((f.ident, f.line) for f in lint_tree(root)
                   if f.check == "kv-fenced")
    # the raw write AND the empty-reason opt-out are findings; the
    # justified inline, the block-above, the fenced receiver and the
    # out-of-package write are not
    assert found == [("cluster.health.renew", 3),
                     ("cluster.health.renew", 6)]


def test_lint_kv_fenced_clean_fixture(tmp_path):
    root = _kv_lint_fixture(tmp_path, """
        def renew(self, kv):
            self.fenced.set("lease/r0", "t")
            kv.delete("lease/r0")   # kv-unfenced: my own key
        """)
    assert [f for f in lint_tree(root) if f.check == "kv-fenced"] == []


def test_kv_fenced_rule_is_clean_on_this_tree():
    """The real tree holds the bar the rule sets: every raw KV write
    under cluster/ and fleet/ is either fenced or carries a reasoned
    inline opt-out."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert [f for f in lint_tree(root) if f.check == "kv-fenced"] == []
