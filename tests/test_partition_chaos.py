"""The ISSUE 20 acceptance drills: partition tolerance across real OS
processes.

Two drills, each over plain subprocesses joined only through a shared
``FileKV`` directory:

* **Split-brain quorum drill** — three ``cluster_worker.py`` ranks
  (phase ``partition``): an asymmetric KV partition cuts rank 2 off
  the wire mid-run.  The minority side must exit its reformation
  attempt typed ``QuorumLossError`` (1 voter of 3) — never form a
  rival mesh; the majority reforms to a 2-rank generation on the
  stale-lease evidence; and when the partition heals, the evicted
  rank's ``FencedKV`` writes are rejected typed ``FencedWriteError``
  by the fence the new generation's rank 0 advanced.  The merged
  journal must tell the whole story (quorum verdicts on every side,
  the fence advance, the fenced zombie write) and render lint-clean
  through the real ``pa-obs`` CLI.

* **Router-death WAL drill** — a ``router_worker.py`` front-end
  SIGKILLs itself at its 7th admission (``fleet.route:kill@7`` armed
  in ITS environment only) mid-way through a 10-request storm against
  one ``fleet_worker.py`` mesh; the mesh is dragged
  (``fleet.route:delay%mesh1``) so a backlog provably survives the
  crash.  The parent replays the WAL into a fresh router and proves
  the exactly-once contract across router incarnations: all 6
  committed admissions resolve exactly once with the bit-correct FFT,
  nothing doubles, nothing is lost, and the final WAL fold agrees.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from pencilarrays_tpu import obs
from pencilarrays_tpu.cluster.kv import FileKV
from pencilarrays_tpu.fleet import FleetRouter, MeshBoard
from pencilarrays_tpu.fleet import wal as walmod
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.resilience import faults

TTL = 2.0
BOOT_S = 90.0


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


# ---------------------------------------------------------------------------
# drill 1: split-brain quorum + zombie fencing across 3 processes
# ---------------------------------------------------------------------------

def _launch_partition_drill(tmp_path, world):
    """Run the ``partition`` phase of ``cluster_worker.py`` across
    ``world`` plain OS processes sharing a FileKV namespace.  Every
    rank must exit 0 — the partition is a *logical* eviction, not a
    process death."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "cluster_worker.py")
    kvroot = os.path.join(str(tmp_path), "kv")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PENCILARRAYS_TPU_FAULTS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(here)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, kvroot, str(world), str(rank),
             str(tmp_path), "partition"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for rank in range(world)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        drained = list(outs)
        for p in procs[len(outs):]:
            try:
                out, _ = p.communicate(timeout=10)
            except Exception:
                out = ""
            drained.append(out or "")
        pytest.fail("partition drill workers timed out (a coordination "
                    "deadlock — exactly what the quorum gate must "
                    "prevent); captured output:\n"
                    + "\n---\n".join(drained))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"partition drill rank {rank} failed:\n{out[-3000:]}")
        assert f"CLUSTER_OK phase=partition rank={rank}" in out, \
            out[-2000:]
    return outs


def test_quorum_partition_across_processes(tmp_path):
    """The split-brain acceptance drill proper: 3 ranks, rank 2
    partitioned mid-run — typed minority exit, majority reformation,
    fenced zombie write, journal lint-clean end to end."""
    world = 3
    outs = _launch_partition_drill(tmp_path, world)

    # the minority exited its reformation typed — 1 voter of 3 — and
    # its post-heal write was rejected by the fence, not by luck
    assert "MINORITY_TYPED have=1 need=2 of=3" in outs[2], outs[2]
    assert "ZOMBIE_FENCED token=(0, 0) fence=(1," in outs[2], outs[2]
    # the majority reformed to a 2-rank generation and agreed in it
    for rank in (0, 1):
        assert f"REFORMED gen=1 world=2 ns=pa.g1" in outs[rank], \
            outs[rank]

    # the merged journal tells the same story, typed on every side
    obsdir = os.path.join(str(tmp_path), "obs")
    events = obs_events.read_journal(obsdir)
    quorum = [e for e in events if e["ev"] == "cluster.quorum"]
    fails = [e for e in quorum if e["verdict"] == "fail"]
    assert fails and all(e["proc"] == 2 for e in fails), quorum
    assert all(e["have"] == [2] and e["need"] == 2 for e in fails)
    passes = [e for e in quorum if e["verdict"] == "pass"
              and e["proc"] in (0, 1)]
    assert passes, quorum
    # the final (post-gather) checks on the majority judged the victim
    # gone on the stale-lease evidence and reformed over it
    assert any(e["of"] == [0, 1] and e["gone"] == [2] for e in passes)
    assert not any(e["verdict"] == "bypass" for e in quorum)
    # the new generation's rank 0 advanced the fence first...
    adv = [e for e in events if e["ev"] == "cluster.reform"
           and e.get("stage") == "fence"]
    assert len(adv) == 1 and adv[0]["proc"] == 0, adv
    assert adv[0]["fence_gen"] == 1
    # ...and the zombie's write was rejected against exactly that fence
    fenced = [e for e in events if e["ev"] == "cluster.fence"]
    assert fenced and all(e["proc"] == 2 for e in fenced), fenced
    assert all(e["gen"] == 0 and e["fence_gen"] == 1 for e in fenced)

    from pencilarrays_tpu.obs.__main__ import main

    assert main(["lint", obsdir]) == 0
    assert main(["timeline", obsdir]) == 0


# ---------------------------------------------------------------------------
# drill 2: router SIGKILL mid-storm -> WAL replay, exactly-once
# ---------------------------------------------------------------------------

def _spawn_mesh(kvroot, mesh, tmpdir, *, fault="", delay_s=None):
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(here),
        "PA_FLEET_TEST_TTL": str(TTL),
        "PENCILARRAYS_TPU_FAULTS": fault,
    })
    if delay_s is not None:
        env["PENCILARRAYS_TPU_FAULTS_DELAY_S"] = str(delay_s)
    env.pop("PENCILARRAYS_TPU_FLEET_MESH", None)
    env.pop("PENCILARRAYS_TPU_CLUSTER_RANK", None)
    return subprocess.Popen(
        [sys.executable, os.path.join(here, "fleet_worker.py"),
         kvroot, str(mesh), tmpdir, "180"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _spawn_router(kvroot, waldir, obsdir, nreq, meshes, *, fault):
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(here),
        "PA_FLEET_TEST_TTL": str(TTL),
        "PENCILARRAYS_TPU_FAULTS": fault,
        "PENCILARRAYS_TPU_OBS": obsdir,
        # the router's own journal identity — distinct from the parent
        # (r0) and the mesh (r1) so the timeline merge sees 3 procs
        "PENCILARRAYS_TPU_CLUSTER_RANK": "3",
    })
    env.pop("PENCILARRAYS_TPU_FLEET_MESH", None)
    return subprocess.Popen(
        [sys.executable, os.path.join(here, "router_worker.py"),
         kvroot, waldir, str(nreq), meshes],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _await_live(kv, meshes):
    board = MeshBoard(kv, ttl=TTL)
    deadline = time.monotonic() + BOOT_S
    while time.monotonic() < deadline:
        if board.live_meshes(meshes) == sorted(meshes):
            return
        time.sleep(0.1)
    raise AssertionError(f"meshes {meshes} never all came alive")


def test_router_sigkill_mid_storm_wal_replay(tmp_path):
    """The WAL acceptance drill proper: the front-end router process is
    SIGKILLed at its 7th admission; a fresh router over the same WAL
    directory replays the log and every one of the 6 committed
    admissions resolves exactly once, bit-correct."""
    kvroot = str(tmp_path / "kv")
    waldir = str(tmp_path / "wal")
    obsdir = str(tmp_path / "obs")
    kv = FileKV(kvroot)
    # the mesh is dragged 0.3 s per routed take, so most of the storm
    # is still unexecuted when the router dies — the replay must
    # re-bind real work, not just harvest finished results
    worker = _spawn_mesh(kvroot, 1, str(tmp_path),
                         fault="fleet.route:delay%mesh1", delay_s=0.3)
    router = None
    try:
        _await_live(kv, [1])
        # incarnation 1: dies by its OWN armed kill at admission #7 —
        # exactly 6 admits committed to the WAL (the fault fires
        # before the 7th admit record is appended)
        rproc = _spawn_router(kvroot, waldir, obsdir, 10, "1",
                              fault="fleet.route:kill@7")
        rout, _ = rproc.communicate(timeout=240)
        assert rproc.returncode == -signal.SIGKILL, (rproc.returncode,
                                                     rout[-2000:])
        assert "ROUTER_READY" in rout and "ROUTER_DRAINED" not in rout

        # incarnation 2: fresh router, same WAL directory
        obs.enable(obsdir)
        router = FleetRouter(kv, ttl=TTL, wal_dir=waldir)
        router.register_mesh(1)
        rep = router.recover()
        assert rep["outcome"] == "clean" and rep["skipped"] == 0, rep
        assert rep["resolved"] + rep["reparked"] == 6, rep
        assert rep["reparked"] >= 1, rep

        # hold the recovered tickets AND their verbatim-logged
        # payloads: the exactly-once proof is numeric, not just counted
        with router._lock:
            held = [(p.ticket, np.asarray(p.payload))
                    for p in router._pending.values()]
        assert len(held) == rep["reparked"]
        assert router.drain(90.0) == 0
        for t, u in held:
            np.testing.assert_allclose(np.asarray(t.result(1.0)),
                                       np.fft.fftn(u),
                                       rtol=1e-3, atol=1e-3)
        stats = router.stats()
        assert stats["completed"] == rep["reparked"]
        assert stats["failed"] == 0 and stats["duplicates"] == 0
        assert stats["pending"] == 0

        # replaying a replayed WAL re-parks nothing: recovery is
        # idempotent across incarnations too
        rep2 = router.recover()
        assert rep2["reparked"] == 0 and rep2["resolved"] == 6, rep2

        # the final WAL fold agrees with the wire: all 6 committed
        # admissions completed ok, exactly once, none pending
        records, skipped = walmod.read_wal(waldir)
        assert skipped == 0
        fold = walmod.replay(records)
        assert fold["pending"] == {} and fold["duplicates"] == 0
        assert len(fold["resolved"]) == 6
        completes = [r for r in records if r["op"] == "complete"]
        assert len(completes) == 6
        assert all(r["outcome"] == "ok" for r in completes)
    finally:
        if router is not None:
            router.close()
        obs.disable()
        kv.set("pa/fleet/stop/m1", "stop")
        if worker.poll() is None:
            try:
                worker.wait(timeout=20)
            except subprocess.TimeoutExpired:
                worker.kill()
        wout, _ = worker.communicate()

    assert "EXITED mesh=1" in wout, wout[-2000:]

    # the replay is journaled fsync-critically and counted — and the
    # merged 3-proc journal (parent, mesh, dead router) renders clean
    # through the real pa-obs CLI, torn tail and all
    events = obs_events.read_journal(obsdir)
    wal_evs = [e for e in events if e["ev"] == "fleet.wal"]
    assert any(e["outcome"] == "clean" and e["resolved"]
               + e["reparked"] == 6 for e in wal_evs), wal_evs
    snap = obs_metrics.registry.snapshot()["counters"]
    assert snap.get("fleet.wal_replays{outcome=clean}", 0) >= 1
    killed = [e for e in events if e["ev"] == "fault"
              and e.get("point") == "fleet.route"]
    assert any(e.get("mode") == "kill" for e in killed)

    from pencilarrays_tpu.obs.__main__ import main

    assert main(["lint", obsdir]) == 0
    assert main(["timeline", obsdir]) == 0
