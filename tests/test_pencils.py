"""Pencil descriptor tests — parity with reference ``test/pencils.jl``
semantics (ranges, sizes, orders, derivation), adapted to the ceil-block
distribution rule (see ``pencil.py`` module docstring)."""

import math

import numpy as np
import pytest

from pencilarrays_tpu import (
    LogicalOrder,
    MemoryOrder,
    Pencil,
    Permutation,
    Topology,
    local_data_range,
    make_pencil,
)
from pencilarrays_tpu.parallel.pencil import complete_dims


def test_local_data_range():
    # ceil-block rule: contiguous, disjoint, covers 0..n-1
    for n in (1, 5, 29, 31, 42, 64):
        for P in (1, 2, 3, 4, 7, 8):
            rs = [local_data_range(p, P, n) for p in range(P)]
            flat = [i for r in rs for i in r]
            assert flat == list(range(n))
            b = -(-n // P)
            assert all(len(r) <= b for r in rs)


def test_complete_dims():
    assert complete_dims(3, (1, 2), (4, 5)) == (1, 4, 5)
    assert complete_dims(4, (0,), (7,), fill=2) == (7, 2, 2, 2)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def test_pencil_basic(topo):
    pen = Pencil(topo, (42, 31, 29), (1, 2))
    assert pen.ndims == 3
    assert pen.decomposition == (1, 2)
    assert pen.size_global() == (42, 31, 29)
    assert pen.size_global(MemoryOrder) == (42, 31, 29)
    # dim1 over 2 devices: ceil(31/2)=16 -> padded 32; dim2 over 4: ceil(29/4)=8 -> 32
    assert pen.padded_global_shape == (42, 32, 32)
    assert pen.decomp_axis_name(0) is None
    assert pen.decomp_axis_name(1) == "p1"
    assert pen.decomp_axis_name(2) == "p2"
    assert pen.proc_count(1) == 2 and pen.proc_count(2) == 4


def test_default_decomposition(devices):
    pen = make_pencil((42, 31, 29))
    # reference default_decomposition decomposes the last N-1 dims
    assert pen.decomposition == (1, 2)
    assert sorted(pen.topology.dims, reverse=True) == [4, 2]


def test_range_local(topo):
    pen = Pencil(topo, (42, 31, 29), (1, 2))
    r00 = pen.range_local((0, 0))
    assert r00 == (range(0, 42), range(0, 16), range(0, 8))
    r13 = pen.range_local((1, 3))
    assert r13 == (range(0, 42), range(16, 31), range(24, 29))
    # disjoint cover of the global domain per dim
    covered = np.zeros((42, 31, 29), dtype=int)
    for rank in range(8):
        rr = pen.range_remote(rank)
        covered[np.ix_(*[list(r) for r in rr])] += 1
    assert (covered == 1).all()


def test_size_local_and_to_local(topo):
    pen = Pencil(topo, (42, 31, 29), (1, 2))
    assert pen.size_local((0, 0)) == (42, 16, 8)
    assert pen.size_local((1, 3)) == (42, 15, 5)
    assert pen.padded_size_local() == (42, 16, 8)
    assert pen.to_local((10, 20, 27), (1, 3)) == (10, 4, 3)
    assert pen.length_global() == 42 * 31 * 29
    total = sum(pen.length_local(pen.topology.coords(r)) for r in range(8))
    assert total == pen.length_global()


def test_permutation_orders(topo):
    perm = Permutation(2, 0, 1)
    pen = Pencil(topo, (42, 31, 29), (1, 2), permutation=perm)
    assert pen.size_global(LogicalOrder) == (42, 31, 29)
    assert pen.size_global(MemoryOrder) == (29, 42, 31)
    assert pen.size_local((0, 0), MemoryOrder) == (8, 42, 16)
    assert pen.padded_size_global(MemoryOrder) == (32, 42, 32)
    assert pen.range_local((0, 0), MemoryOrder) == (
        range(0, 8), range(0, 42), range(0, 16))


def test_partition_spec(topo):
    pen = Pencil(topo, (42, 31, 29), (1, 2))
    assert tuple(pen.partition_spec()) == (None, "p1", "p2")
    perm = Permutation(2, 0, 1)
    pen_p = Pencil(topo, (42, 31, 29), (1, 2), permutation=perm)
    assert tuple(pen_p.partition_spec()) == ("p2", None, "p1")
    assert tuple(pen_p.partition_spec(extra_ndims=2)) == ("p2", None, "p1", None, None)
    s = pen.sharding()
    assert s.mesh.axis_names == ("p1", "p2")


def test_replace_and_similar(topo):
    pen = Pencil(topo, (42, 31, 29), (1, 2))
    pen_y = pen.replace(decomp_dims=(0, 2))
    assert pen_y.decomposition == (0, 2)
    assert pen_y.topology is pen.topology
    assert pen_y.size_global() == pen.size_global()
    pen2 = pen.similar(global_shape=(16, 16, 16))
    assert pen2.size_global() == (16, 16, 16)
    assert pen2.decomposition == pen.decomposition
    # permutation replacement
    pen_p = pen.replace(permutation=Permutation(1, 2, 0))
    assert pen_p.permutation == Permutation(1, 2, 0)
    assert pen.permutation.is_identity()


def test_validation(topo):
    with pytest.raises(ValueError):
        Pencil(topo, (8, 8, 8), (1,))  # M mismatch
    with pytest.raises(ValueError):
        Pencil(topo, (8, 8, 8), (1, 1))  # duplicate
    with pytest.raises(ValueError):
        Pencil(topo, (8, 8, 8), (1, 5))  # out of range


def test_empty_rank_warning(topo):
    # 2 rows over 4 devices on axis p2 -> empty blocks (Pencils.jl:193-218)
    with pytest.warns(UserWarning, match="no data"):
        Pencil(topo, (8, 8, 2), (1, 2))


def test_eq_hash(topo):
    a = Pencil(topo, (8, 8, 8), (1, 2))
    b = Pencil(topo, (8, 8, 8), (1, 2))
    assert a == b and hash(a) == hash(b)
    assert a != a.replace(decomp_dims=(0, 2))
    assert a != a.replace(permutation=Permutation(1, 0, 2))


def test_full_decomposition(topo):
    # M == N decomposition is allowed (test/pencils.jl:523-542)
    pen = Pencil(topo, (8, 8), (0, 1))
    assert pen.size_local((0, 0)) == (4, 2)
    assert pen.padded_global_shape == (8, 8)


def test_axes_all(topo):
    pen = Pencil(topo, (42, 31, 29), (1, 2))
    table = pen.axes_all
    assert table.shape == (2, 4)
    assert table[(1, 3)] == pen.range_local((1, 3))
