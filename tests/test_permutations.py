"""Permutation algebra tests — semantics parity with the reference's
``test/permutations.jl`` (StaticPermutations behavior, 0-based here)."""

import itertools

import pytest

from pencilarrays_tpu import NO_PERMUTATION, NoPermutation, Permutation
from pencilarrays_tpu.utils.permutations import as_permutation, identity_permutation


def test_apply_basic():
    # Julia: Permutation(2,3,1) * (x1,x2,x3) == (x2,x3,x1); 0-based: (1,2,0)
    p = Permutation(1, 2, 0)
    assert p.apply(("a", "b", "c")) == ("b", "c", "a")
    assert p.invapply(p.apply((1, 2, 3))) == (1, 2, 3)
    assert p.apply(p.invapply((1, 2, 3))) == (1, 2, 3)


def test_invalid():
    with pytest.raises(ValueError):
        Permutation(0, 0, 1)
    with pytest.raises(ValueError):
        Permutation(1, 2, 3)
    with pytest.raises(ValueError):
        Permutation(2, 0, 1).apply((1, 2))


def test_identity_and_nopermutation():
    np_ = NoPermutation()
    assert np_ is NO_PERMUTATION  # singleton
    assert np_.apply((3, 1, 2)) == (3, 1, 2)
    assert np_.invapply((3, 1, 2)) == (3, 1, 2)
    assert np_ == Permutation(0, 1, 2)
    assert Permutation(0, 1, 2) == np_
    assert Permutation(0, 1, 2).is_identity()
    assert not Permutation(1, 0, 2).is_identity()
    assert identity_permutation(4) == NO_PERMUTATION


def test_compose_inverse_exhaustive():
    # (p * q).apply(t) == p.apply(q.apply(t)) for every pair of 3-perms.
    t = ("x", "y", "z")
    for a in itertools.permutations(range(3)):
        for b in itertools.permutations(range(3)):
            p, q = Permutation(a), Permutation(b)
            assert (p * q).apply(t) == p.apply(q.apply(t))
            assert (p * p.inverse()).is_identity()
            assert (p.inverse() * p).is_identity()
            # relative permutation r = p / q satisfies r * q == p
            r = p / q
            assert (r * q) == p


def test_compose_with_nopermutation():
    p = Permutation(2, 0, 1)
    assert (p * NO_PERMUTATION) == p
    assert (NO_PERMUTATION * p) == p
    assert (NO_PERMUTATION * NO_PERMUTATION) == NO_PERMUTATION
    assert NO_PERMUTATION.inverse() is NO_PERMUTATION


def test_append_prepend():
    # Reference ``append`` identity-extends for extra dims (arrays.jl:34-47).
    p = Permutation(1, 0)
    assert p.append(2) == Permutation(1, 0, 2, 3)
    assert p.prepend(2) == Permutation(0, 1, 3, 2)
    assert NO_PERMUTATION.append(3) is NO_PERMUTATION


def test_hash_eq():
    assert hash(Permutation(1, 0)) == hash(Permutation(1, 0))
    s = {Permutation(1, 0), Permutation(1, 0), NO_PERMUTATION}
    assert len(s) == 2
    # eq/hash contract: identity Permutation == NoPermutation
    assert hash(Permutation(0, 1, 2)) == hash(NO_PERMUTATION)
    assert len({Permutation(0, 1, 2), NO_PERMUTATION}) == 1


def test_as_permutation():
    assert as_permutation(None, 3) is NO_PERMUTATION
    assert as_permutation((2, 0, 1), 3) == Permutation(2, 0, 1)
    with pytest.raises(ValueError):
        as_permutation((1, 0), 3)


def test_axes_for_transpose():
    import numpy as np

    x = np.arange(24).reshape(2, 3, 4)
    p = Permutation(2, 0, 1)
    y = np.transpose(x, p.axes())
    assert y.shape == p.apply(x.shape)
