"""Precision as a runtime serving lever (ISSUE 19): the pressure
gate's degrade rung, calibrated-envelope rung selection, admission-time
plan-variant swapping, and the ``serve.precision`` journal contract.

Boundary contracts under test:

* the four-state gate ladder is hysteretic and flap-free: shed never
  de-escalates at the degrade mark, recovery is only at low water;
* ``degrades()`` is true in every pressure state — under ``shed`` the
  rung is what keeps a ``max_rel_l2`` tenant SERVED where a budget-less
  one is rejected typed;
* rung selection is envelope-driven: a budget below every calibrated
  envelope downgrades nothing, a generous one lands on fp8, and a plan
  already at its floor is left alone;
* degraded traffic NEVER coalesces with full-precision traffic (the
  coalesce key is rebuilt from the variant's ``plan_key``) and the
  registry holds per-precision compiled executables;
* every applied downgrade journals one fsync-critical
  ``serve.precision`` record (schema v7) carrying the promised
  envelope and the budget it fit under;
* with no ``max_rel_l2`` declared (or no ``degrade_water_s`` armed),
  behavior is the PR-18 gate bit-for-bit.
"""

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.serve import (
    SLO,
    AdmissionError,
    PlanService,
    PressurePolicy,
    select_rung,
    wire_error_envelope,
)
from pencilarrays_tpu.serve.shed import PressureGate

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _host(rng, shape):
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


# ---------------------------------------------------------------------------
# policy + gate ladder
# ---------------------------------------------------------------------------


def test_degrade_policy_validation():
    PressurePolicy(high_water_s=1.0, low_water_s=0.1, degrade_water_s=0.5)
    with pytest.raises(ValueError):        # at/above high water
        PressurePolicy(high_water_s=1.0, low_water_s=0.1,
                       degrade_water_s=1.0)
    with pytest.raises(ValueError):        # at/below low water
        PressurePolicy(high_water_s=1.0, low_water_s=0.5,
                       degrade_water_s=0.5)


def test_gate_four_state_ladder_hysteresis():
    g = PressureGate(PressurePolicy(high_water_s=1.0, low_water_s=0.1,
                                    degrade_water_s=0.5))
    assert g.state == "ok"
    assert g.update(0.3) == "ok"           # below degrade: still open
    assert g.update(0.6) == "degrade"      # degrade mark crossed
    assert g.update(0.3) == "degrade"      # hysteresis band holds
    assert g.update(1.5) == "shed"
    assert g.update(0.7) == "shed"         # shed HOLDS at the degrade
    assert g.update(0.3) == "shed"         # band — no shed/degrade flap
    assert g.update(0.05) == "ok"          # recovery only at low water
    assert g.update(2.5) == "evict"
    assert g.update(0.7) == "shed"         # evict de-escalates one rung
    assert g.update(0.05) == "ok"
    # escalation straight from ok to evict is immediate
    assert g.update(9.9) == "evict"


def test_gate_without_degrade_mark_is_three_state():
    """degrade_water_s=None keeps the PR-15 machine bit-for-bit."""
    g = PressureGate(PressurePolicy(high_water_s=1.0, low_water_s=0.5))
    assert g.update(0.9) == "ok"           # the whole band holds open
    assert g.update(1.2) == "shed"
    assert g.update(0.9) == "shed"
    assert g.update(0.5) == "ok"
    assert g.transitions == 2              # storm -> recover, exactly two


def test_degrades_vs_sheds_predicates():
    g = PressureGate(PressurePolicy(high_water_s=1.0, low_water_s=0.1,
                                    degrade_water_s=0.5))
    g.update(0.6)                          # -> degrade
    assert g.degrades(0, 1) and not g.sheds(0, 1)
    assert not g.degrades(1, 1)            # protected tier: never
    g.update(1.5)                          # -> shed
    assert g.degrades(0, 1) and g.sheds(0, 1)
    g.update(2.5)                          # -> evict
    assert g.degrades(0, 1) and g.sheds(0, 1) and g.evicting()


# ---------------------------------------------------------------------------
# calibrated envelopes + rung selection
# ---------------------------------------------------------------------------


def test_wire_error_envelope_reads_artifact(tmp_path, monkeypatch):
    import json

    doc = {"workload_x": {"bf16": {"rel_err_l2": 0.002},
                          "fp8_e4m3": {"rel_err_l2": 0.03}},
           "workload_y": {"fp8_e4m3": {"rel_err_l2": 0.02}}}
    p = tmp_path / "BENCH_WIRE.json"
    p.write_text(json.dumps(doc))
    monkeypatch.setenv("PENCILARRAYS_TPU_BENCH_WIRE_PATH", str(p))
    # 2x the worst rel_err_l2 recorded anywhere for the format
    assert wire_error_envelope("fp8_e4m3") == pytest.approx(0.06)
    assert wire_error_envelope("bf16") == pytest.approx(0.004)
    # a format the artifact has no numbers for: conservative fallback
    assert wire_error_envelope("fp8_e5m2") == pytest.approx(0.16)


def test_select_rung_is_envelope_driven(tmp_path, monkeypatch):
    import json

    p = tmp_path / "BENCH_WIRE.json"
    p.write_text(json.dumps({
        "w": {"bf16": {"rel_err_l2": 0.002},
              "fp8_e4m3": {"rel_err_l2": 0.03}}}))
    monkeypatch.setenv("PENCILARRAYS_TPU_BENCH_WIRE_PATH", str(p))
    assert select_rung(1e-5) is None                   # too tight
    assert select_rung(0.01) == ("bf16", pytest.approx(0.004))
    assert select_rung(0.5) == ("fp8_e4m3", pytest.approx(0.06))
    # deepest-admissible from a 16-bit floor; None at the fp8 floor
    assert select_rung(0.5, "bf16")[0] == "fp8_e4m3"
    assert select_rung(0.01, "bf16") is None
    assert select_rung(0.5, "fp8_e4m3") is None


# ---------------------------------------------------------------------------
# the serving lever end to end
# ---------------------------------------------------------------------------


def _degrade_service(plan, **slos):
    svc = PlanService(
        max_batch=4, max_wait_s=60.0, slos=dict(slos),
        pressure=PressurePolicy(high_water_s=1.0, low_water_s=0.1,
                                degrade_water_s=0.5))
    # pin the forced gate state: the live drain projection of a test
    # queue would recover to "ok" between submissions
    svc._gate.update = lambda *a, **k: svc._gate._state
    return svc


def test_degrade_rung_serves_within_budget(devices, tmp_path):
    obs.enable(str(tmp_path))
    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 20), dtype=np.complex64)
    svc = _degrade_service(
        plan,
        gold=SLO(shed_priority=2),
        flex=SLO(shed_priority=0, max_rel_l2=0.5),
        rigid=SLO(shed_priority=0))
    svc._gate._state = "degrade"
    rng = np.random.default_rng(0)
    u = _host(rng, (16, 12, 20))
    t_gold = svc.submit("gold", u, plan=plan)
    t_flex = svc.submit("flex", u, plan=plan)
    t_rigid = svc.submit("rigid", u, plan=plan)
    # protected + no-budget tenants keep the full-precision key; the
    # budget tenant moved to its own (never-coalescing) variant key
    assert t_gold.key == f"fft:{plan.plan_key()}:forward"
    assert t_rigid.key == t_gold.key
    assert t_flex.key != t_gold.key
    svc.drain()
    ref = np.fft.fftn(u)
    r_gold = np.asarray(t_gold.result(30).logical())
    r_flex = np.asarray(t_flex.result(30).logical())
    rel_flex = np.linalg.norm(r_flex - ref) / np.linalg.norm(ref)
    rel_gold = np.linalg.norm(r_gold - ref) / np.linalg.norm(ref)
    assert rel_gold < 1e-5                 # full precision untouched
    assert 1e-4 < rel_flex < 0.5           # degraded, inside budget
    # the registry holds BOTH compiled variants, keyed apart
    keys = svc.registry.keys()
    assert t_gold.key.split(":")[1] in keys
    assert t_flex.key.split(":")[1] in keys
    # journal: one fsync-critical serve.precision record, schema v7
    svc.close()
    obs.disable()
    evs = obs_events.read_journal(str(tmp_path))
    prec = [e for e in evs if e["ev"] == "serve.precision"]
    assert len(prec) == 1
    rec = prec[0]
    assert rec["v"] >= 7
    assert rec["tenant"] == "flex"
    assert rec["wire_from"] == "full"
    assert rec["wire_to"] in ("bf16", "fp8_e4m3")
    assert rec["envelope"] <= rec["max_rel_l2"] == 0.5
    assert rec["trace"] and rec["gate"] == "degrade"
    from pencilarrays_tpu.obs.schema import lint_journal
    assert lint_journal(evs) == []
    # the request-flow join: the degraded request's trace reaches its
    # serve.request record too (pa-obs request reconstructs the path)
    reqs = [e for e in evs if e["ev"] == "serve.request"
            and e.get("trace") == rec["trace"]]
    assert len(reqs) == 1 and reqs[0]["tenant"] == "flex"


def test_shed_state_serves_budget_tenant_sheds_rest(devices):
    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 20), dtype=np.complex64)
    svc = _degrade_service(
        plan,
        gold=SLO(shed_priority=2),
        flex=SLO(shed_priority=0, max_rel_l2=0.5),
        rigid=SLO(shed_priority=0))
    svc._gate._state = "shed"
    rng = np.random.default_rng(1)
    u = _host(rng, (16, 12, 20))
    t_gold = svc.submit("gold", u, plan=plan)      # protected: served
    t_flex = svc.submit("flex", u, plan=plan)      # degraded: served
    with pytest.raises(AdmissionError) as ei:      # budget-less: shed
        svc.submit("rigid", u, plan=plan)
    assert ei.value.reason == "shed"
    svc.drain()
    assert t_gold.result(30) is not None
    assert t_flex.result(30) is not None
    svc.close()


def test_degraded_traffic_never_coalesces_with_full(devices):
    """Two same-plan requests, one degraded: they must form TWO
    batches (precisions never mix inside one dispatch)."""
    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 20), dtype=np.complex64)
    svc = _degrade_service(
        plan,
        gold=SLO(shed_priority=2),
        flex=SLO(shed_priority=0, max_rel_l2=0.5))
    rng = np.random.default_rng(2)
    svc._gate._state = "ok"
    t_a = svc.submit("gold", _host(rng, (16, 12, 20)), plan=plan)
    svc._gate._state = "degrade"
    t_b = svc.submit("flex", _host(rng, (16, 12, 20)), plan=plan)
    assert t_a.key != t_b.key
    batches = svc.queue.take_ready(flush=True)
    assert svc.queue.take_ready(flush=True) == []
    for b in batches:
        svc._dispatch(b)
    assert len(batches) == 2
    assert {b.key for b in batches} == {t_a.key, t_b.key}
    assert all(len(b.entries) == 1 for b in batches)
    svc.close()


def test_no_budget_no_degrade_is_pr18_behavior(devices):
    """Without max_rel_l2 (or under an unarmed gate) nothing changes:
    same keys, bit-identical results to a no-pressure service."""
    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 20), dtype=np.complex64)
    rng = np.random.default_rng(3)
    u = _host(rng, (16, 12, 20))
    base = PlanService(max_batch=4, max_wait_s=60.0)
    t0 = base.submit("t", u, plan=plan)
    base.drain()
    r0 = np.asarray(t0.result(30).logical())
    base.close()
    svc = _degrade_service(plan, t=SLO(shed_priority=0),
                           gold=SLO(shed_priority=2))
    svc._gate._state = "degrade"
    t1 = svc.submit("t", u, plan=plan)
    assert t1.key == t0.key
    svc.drain()
    r1 = np.asarray(t1.result(30).logical())
    svc.close()
    np.testing.assert_array_equal(r0, r1)


def test_registry_compiled_variants_keyed_apart(devices):
    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=np.float32)
    from pencilarrays_tpu.serve import PlanRegistry

    reg = PlanRegistry()
    reg.register(plan)
    v = plan.with_wire_dtype("fp8_e4m3")
    reg.register(v)
    c_full = reg.compiled(plan, ())
    c_fp8 = reg.compiled(v, ())
    assert c_full is not c_fp8
    # resolving again hits the SAME executables — per-precision caching
    assert reg.compiled(plan, ()) is c_full
    assert reg.compiled(v, ()) is c_fp8
