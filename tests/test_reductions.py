"""Distributed reduction tests — parity with reference ``src/reductions.jl``
semantics; padding-masking is the TPU-specific hazard under test (ragged
shapes chosen so every decomposed dim is padded)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Permutation, Topology
from pencilarrays_tpu import ops


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


@pytest.fixture
def setup(topo):
    shape = (9, 11, 13)  # none divisible: padding everywhere
    u = np.random.default_rng(3).standard_normal(shape)
    pen = Pencil(topo, shape, (1, 2), permutation=Permutation(2, 0, 1))
    x = PencilArray.from_global(pen, u)
    return u, x


def test_sum_mean(setup):
    u, x = setup
    assert np.isclose(float(ops.sum(x)), u.sum())
    assert np.isclose(float(ops.mean(x)), u.mean())


def test_min_max(setup):
    u, x = setup
    # padding is zero-filled; u may be all-positive in a block, so masking
    # correctness shows up as exact agreement with numpy
    assert float(ops.minimum(x)) == pytest.approx(u.min())
    assert float(ops.maximum(x)) == pytest.approx(u.max())


def test_min_positive_data(topo):
    # all-positive data: an unmasked zero padding would corrupt min()
    shape = (9, 11, 13)
    u = np.abs(np.random.default_rng(4).standard_normal(shape)) + 5.0
    pen = Pencil(topo, shape, (1, 2))
    x = PencilArray.from_global(pen, u)
    assert float(ops.minimum(x)) == pytest.approx(u.min())
    assert float(ops.minimum(x)) >= 5.0


def test_any_all(topo):
    shape = (6, 10, 7)
    pen = Pencil(topo, shape, (1, 2))
    u = np.zeros(shape)
    x = PencilArray.from_global(pen, u)
    assert not bool(ops.any(x))
    # all() with zero padding would be corrupted without masking
    v = PencilArray.from_global(pen, np.ones(shape))
    assert bool(ops.all(v))
    u2 = np.zeros(shape)
    u2[5, 9, 6] = 1.0  # single hot element in the last block
    x2 = PencilArray.from_global(pen, u2)
    assert bool(ops.any(x2))
    # predicate forms (reference any/all with function)
    assert bool(ops.all(v, pred=lambda d: d > 0.5))
    assert not bool(ops.any(v, pred=lambda d: d > 1.5))


def test_norms_dot(setup):
    u, x = setup
    assert np.isclose(float(ops.norm(x)), np.linalg.norm(u.ravel()))
    assert np.isclose(float(ops.norm(x, 1)), np.abs(u).sum())
    assert np.isclose(float(ops.norm(x, np.inf)), np.abs(u).max())
    assert np.isclose(float(ops.dot(x, x)), (u * u).sum())


def test_mapreduce_zipped(setup):
    u, x = setup
    y = x * 2.0
    got = ops.mapreduce(lambda a, b: a * b, jnp.sum, x, y, identity=0)
    assert np.isclose(float(got), (u * (2 * u)).sum())


def test_count_nonzero(topo):
    shape = (6, 10, 7)
    pen = Pencil(topo, shape, (1, 2))
    u = np.zeros(shape)
    u[0, 0, 0] = 1.0
    u[5, 9, 6] = 2.0
    x = PencilArray.from_global(pen, u)
    assert int(ops.count_nonzero(x)) == 2


def test_minmax_bool_int(topo):
    shape = (6, 10, 7)
    pen = Pencil(topo, shape, (1, 2))
    b = PencilArray.from_global(pen, np.ones(shape, dtype=bool))
    assert bool(ops.minimum(b)) is True and bool(ops.maximum(b)) is True
    i = PencilArray.from_global(pen, np.arange(np.prod(shape)).reshape(shape))
    assert int(ops.minimum(i)) == 0
    assert int(ops.maximum(i)) == np.prod(shape) - 1
    c = PencilArray.from_global(pen, np.ones(shape, dtype=np.complex64))
    with pytest.raises(TypeError, match="no ordering"):
        ops.minimum(c)


def test_complex_normal_variance(topo):
    import jax as _jax
    from pencilarrays_tpu.ops import normal

    pen = Pencil(topo, (32, 32, 32), (1, 2))
    z = normal(pen, _jax.random.key(0), dtype=jnp.complex64)
    var = float(ops.mean(z.map(lambda d: jnp.abs(d) ** 2)))
    assert 0.9 < var < 1.1  # standard complex normal: total variance 1


def test_reductions_under_jit(setup):
    u, x = setup

    @jax.jit
    def f(a):
        return ops.norm(a) + ops.sum(a)

    assert np.isclose(float(f(x)), np.linalg.norm(u.ravel()) + u.sum())


def test_complex_dot(topo):
    shape = (6, 10, 7)
    pen = Pencil(topo, shape, (1, 2))
    rng = np.random.default_rng(5)
    u = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    v = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    x = PencilArray.from_global(pen, u)
    y = PencilArray.from_global(pen, v)
    assert np.isclose(complex(ops.dot(x, y)), np.vdot(u, v))
