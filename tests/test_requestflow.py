"""Request-scoped tracing (obs/requestflow.py): hostile reconstruction
+ the burn-rate monitor's alert discipline.

The contracts under test (ISSUE 18 satellites):

* a coalesced batch journals ONE dispatch span shared by its B member
  traces — every member reconstructs through it (``fan_in``), none
  invents a private dispatch;
* reconstruction over wreckage DEGRADES: a missing mesh journal, a
  torn tail, and pre-v6 (traceless) journals each produce warnings,
  never exceptions — and the ``pa-obs request``/``requests`` exit
  codes are pinned (found 0 / unknown id 1 / index always 0; warnings
  alone never fail);
* :class:`~pencilarrays_tpu.serve.slo.BurnRateMonitor` alerts are
  edge-triggered with hysteresis (one alert per crossing), gated by
  the ``min_events`` floor, and the sliding window actually evicts.
"""

import json
import os

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.obs.__main__ import main
from pencilarrays_tpu.obs.requestflow import (
    RequestTrace,
    list_requests,
    reconstruct_request,
    render_index,
    render_request,
)
from pencilarrays_tpu.obs.schema import lint_journal
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.serve import PlanService
from pencilarrays_tpu.serve.slo import BurnRateMonitor

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


# ---------------------------------------------------------------------------
# synthetic journals: full control over ranks, tears and versions
# ---------------------------------------------------------------------------

def _rec(proc, seq, t, ev, v=6, **fields):
    """One schema-clean journal record with the full envelope."""
    rec = {"v": v, "ev": ev, "run": f"run-r{proc}", "proc": proc,
           "seq": seq, "t_wall": t, "t_mono": t,
           "step_idx": 0, "epoch": 0}
    rec.update(fields)
    return rec


def _write_rank(directory, proc, records):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"journal.r{proc}.jsonl")
    with open(path, "a") as f:
        for r in records:
            f.write(json.dumps(r, separators=(",", ":")) + "\n")
    return path


A, B, C = "aaaa000011112222", "bbbb000011112222", "cccc000011112222"


def _mesh_story(t0=100.0):
    """Rank 1's story: three admissions coalescing into one dispatch."""
    recs = [_rec(1, 1, t0, "run.start", pid=1)]
    for i, tr in enumerate((A, B, C)):
        recs.append(_rec(1, 2 + i, t0 + 0.01 * i, "serve.request",
                         tenant="acme", req=i, kind="fft", key="k",
                         nbytes=1024, trace=tr))
    recs.append(_rec(1, 5, t0 + 0.05, "serve.coalesce", key="k", n=3,
                     reqs=[0, 1, 2], reason="full", wait_s=0.04,
                     trace=A, traces=[A, B, C]))
    recs.append(_rec(1, 6, t0 + 0.06, "serve.dispatch", key="k", n=3,
                     tenants=["acme"], score_bytes=3072, reason="full",
                     lane=0, chain="*", trace=A, traces=[A, B, C]))
    for i, tr in enumerate((A, B, C)):
        recs.append(_rec(1, 7 + i, t0 + 0.2 + 0.01 * i, "serve.complete",
                         tenant="acme", req=i, outcome="ok",
                         seconds=0.1, key="k", trace=tr))
    return recs


def _router_story(t0=100.0):
    recs = [_rec(0, 1, t0 - 1.0, "run.start", pid=0)]
    for i, tr in enumerate((A, B, C)):
        recs.append(_rec(0, 2 + i, t0 - 0.5 + 0.01 * i, "fleet.route",
                         ticket=f"t{i}", tenant="acme", mesh=1,
                         reason="placed", score_bytes=1024, trace=tr))
    return recs


def test_synthetic_fan_in_shared_dispatch_span(tmp_path):
    """Every member of a coalesced batch reconstructs THROUGH the one
    shared dispatch record — joined by ``traces`` membership."""
    d = str(tmp_path / "obs")
    _write_rank(d, 0, _router_story())
    _write_rank(d, 1, _mesh_story())
    assert lint_journal(obs_events.read_journal(d)) == []
    for tr in (A, B, C):        # the leader AND both followers
        rt, warnings = reconstruct_request(d, tr)
        assert isinstance(rt, RequestTrace) and rt.trace == tr
        assert warnings == []
        assert rt.fan_in == 3
        assert rt.ranks == [0, 1]
        assert rt.outcome == "ok" and rt.tenant == "acme"
        evs = [e["ev"] for e in rt.events]
        # one route, one shared coalesce+dispatch, ONE own completion
        assert evs.count("fleet.route") == 1
        assert evs.count("serve.coalesce") == 1
        assert evs.count("serve.dispatch") == 1
        assert evs.count("serve.complete") == 1
        assert {"wire_s", "admission_wait_s", "coalesce_wait_s",
                "compute_s", "lane_wait_s"} <= set(rt.critical_path)
        assert rt.critical_path["compute_s"] == pytest.approx(0.1)
        text = render_request(rt)
        assert tr in text and "critical path:" in text
    # the B and C spans are the SAME journal record as A's, not copies
    rt_a, _ = reconstruct_request(d, A)
    rt_b, _ = reconstruct_request(d, B)
    disp_a = next(e for e in rt_a.events if e["ev"] == "serve.dispatch")
    disp_b = next(e for e in rt_b.events if e["ev"] == "serve.dispatch")
    assert disp_a["seq"] == disp_b["seq"] == 6
    # the index counts shared fan-in records toward every member
    summaries, warnings = list_requests(d)
    assert warnings == []
    assert [s["trace"] for s in summaries] == [A, B, C]
    for s in summaries:
        # route + request + coalesce + dispatch + complete — the
        # shared fan-in records count ONCE for each member
        assert s["events"] == 5 and s["outcome"] == "ok"
        assert s["ranks"] == [0, 1]
    assert A in render_index(summaries)


def test_missing_mesh_journal_degrades_to_warnings(tmp_path):
    """The placed mesh's journal never made it to shared storage: the
    reconstruction keeps the router's half of the story and WARNS —
    both about the rank hole and the missing admission record."""
    d = str(tmp_path / "obs")
    _write_rank(d, 0, _router_story())
    # rank 2 exists so the rank-1 hole is visible as a hole
    _write_rank(d, 2, [_rec(2, 1, 99.5, "run.start", pid=2)])
    rt, warnings = reconstruct_request(d, A)
    assert rt is not None and rt.trace == A
    assert rt.ranks == [0]
    assert rt.outcome is None and rt.fan_in is None
    assert any("rank 1: no journal found" in w for w in warnings)
    assert any("no serve.request record" in w for w in warnings)
    assert any("no serve.complete record" in w for w in warnings)
    # warnings alone never fail the CLI; an unknown id does
    assert main(["request", d, A]) == 0
    assert main(["requests", d]) == 0
    assert main(["request", d, "feedfacedeadbeef"]) == 1


def test_torn_tail_degrades_to_warnings(tmp_path):
    """A SIGKILL mid-append tears the mesh journal's final line (and a
    disk hiccup mangles a mid-file one): both are warnings, and every
    intact record still reconstructs."""
    d = str(tmp_path / "obs")
    _write_rank(d, 0, _router_story())
    path = _write_rank(d, 1, _mesh_story())
    with open(path) as f:
        lines = f.read().splitlines()
    lines[3] = lines[3][: len(lines[3]) // 2]       # mid-file mangle
    torn = "\n".join(lines) + "\n" + '{"v":6,"ev":"serve.comp'
    with open(path, "w") as f:
        f.write(torn)
    rt, warnings = reconstruct_request(d, A)
    assert rt is not None and rt.outcome == "ok"
    assert any("torn final line" in w for w in warnings)
    assert any("unparseable mid-file" in w for w in warnings)
    assert main(["request", d, A]) == 0
    assert main(["requests", d]) == 0


def test_v5_journals_stay_clean_and_traceless(tmp_path):
    """Pre-v6 journals carry no trace fields: they lint clean (the
    requirement is versioned), index empty, and the CLI reports rather
    than raises."""
    d = str(tmp_path / "obs")
    recs = [
        _rec(0, 1, 10.0, "run.start", v=5, pid=0),
        _rec(0, 2, 10.1, "serve.request", v=5, tenant="acme", req=0,
             kind="fft", key="k", nbytes=64),
        _rec(0, 3, 10.2, "serve.dispatch", v=5, key="k", n=1,
             tenants=["acme"], score_bytes=64, reason="full",
             lane=0, chain="*"),
        _rec(0, 4, 10.3, "serve.complete", v=5, tenant="acme", req=0,
             outcome="ok", seconds=0.05, key="k"),
    ]
    _write_rank(d, 0, recs)
    assert lint_journal(obs_events.read_journal(d)) == []
    summaries, warnings = list_requests(d)
    assert summaries == [] and warnings == []
    assert "no traced requests" in render_index(summaries)
    rt, warnings = reconstruct_request(d, A)
    assert rt is None
    assert main(["requests", d]) == 0
    assert main(["request", d, A]) == 1
    # and an empty directory is a warning, not a crash
    empty = str(tmp_path / "nothing")
    os.makedirs(empty)
    rt, warnings = reconstruct_request(empty, A)
    assert rt is None
    assert any("no journal files" in w for w in warnings)
    assert main(["request", empty, A]) == 1
    assert main(["requests", empty]) == 0


# ---------------------------------------------------------------------------
# the real service: coalesced fan-in stamps end to end
# ---------------------------------------------------------------------------

def test_real_coalesced_batch_shares_one_dispatch(tmp_path, devices):
    """Three same-plan requests coalescing into ONE batch journal one
    coalesce/dispatch pair carrying all three minted trace ids — and
    each member reconstructs through the shared span."""
    jdir = str(tmp_path / "obs")
    obs.enable(jdir)
    try:
        topo = pa.Topology((2,), devices=devices[:2])
        plan = PencilFFTPlan(topo, (8, 6, 4))
        rng = np.random.default_rng(0)
        # max_batch=3 + a long wait: the batch dispatches exactly when
        # the third member arrives — deterministically ONE batch
        svc = PlanService(max_batch=3, max_wait_s=60.0)
        us = [(rng.standard_normal((8, 6, 4))
               + 1j * rng.standard_normal((8, 6, 4))).astype(np.complex64)
              for _ in range(3)]
        tickets = [svc.submit("acme", u, plan=plan) for u in us]
        assert svc.drain() == 1
        for t, u in zip(tickets, us):
            np.testing.assert_allclose(np.asarray(t.result(5.0)),
                                       np.fft.fftn(u), rtol=1e-3,
                                       atol=1e-3)
        svc.close()
    finally:
        obs.disable()
    events = obs_events.read_journal(jdir)
    assert lint_journal(events) == []
    reqs = [e for e in events if e["ev"] == "serve.request"]
    assert len(reqs) == 3
    minted = [e["trace"] for e in reqs]
    assert len(set(minted)) == 3        # one FRESH id per admission
    disp = [e for e in events if e["ev"] == "serve.dispatch"]
    coal = [e for e in events if e["ev"] == "serve.coalesce"]
    assert len(disp) == 1 and len(coal) == 1
    assert sorted(disp[0]["traces"]) == sorted(minted)
    assert sorted(coal[0]["traces"]) == sorted(minted)
    assert disp[0]["trace"] == disp[0]["traces"][0]
    done = [e for e in events if e["ev"] == "serve.complete"]
    assert sorted(e["trace"] for e in done) == sorted(minted)
    for tr in minted:
        rt, warnings = reconstruct_request(jdir, tr)
        assert rt is not None and warnings == []
        assert rt.fan_in == 3 and rt.outcome == "ok"
        assert main(["request", jdir, tr]) == 0


# ---------------------------------------------------------------------------
# BurnRateMonitor: edge-triggered alerts, hysteresis, eviction
# ---------------------------------------------------------------------------

def test_burn_alert_fires_exactly_once_per_crossing():
    m = BurnRateMonitor(budget=0.1, threshold=2.0, window_s=1000.0,
                        min_events=5)
    alerts = []
    for i in range(20):         # a sustained 100% violation storm
        a = m.note("acme", True, now=float(i))
        if a is not None:
            alerts.append(a)
    assert len(alerts) == 1     # edge-triggered: ONE alert, not 16
    # and it fired the moment the min_events floor was met
    assert alerts[0]["tenant"] == "acme"
    assert alerts[0]["burn_rate"] == pytest.approx(10.0)
    assert alerts[0]["threshold"] == 2.0
    assert alerts[0]["window_s"] == 1000.0
    assert m.burn_rate("acme", now=20.0) == pytest.approx(10.0)


def test_burn_alert_rearms_below_half_threshold():
    """Hysteresis: the alert re-arms only once the rate falls below
    threshold/2, so a rate hovering AT threshold cannot flap."""
    m = BurnRateMonitor(budget=0.1, threshold=2.0, window_s=1e6,
                        min_events=5)
    n_alerts = 0
    t = [0.0]

    def feed(violated, k):
        nonlocal n_alerts
        for _ in range(k):
            t[0] += 1.0
            if m.note("acme", violated, now=t[0]) is not None:
                n_alerts += 1

    feed(True, 5)               # frac 1.0 -> rate 10: first crossing
    assert n_alerts == 1
    feed(True, 10)              # still alerting: silent
    assert n_alerts == 1
    # dilute to frac 15/100 -> rate 1.5: above half-threshold, armed? NO
    feed(False, 85)
    assert m.burn_rate("acme") == pytest.approx(1.5)
    feed(True, 1)               # 16/101 -> 1.58: still not re-armed
    assert n_alerts == 1
    # dilute below half-threshold (frac < 0.1): re-arms
    feed(False, 100)            # 16/201 -> 0.796 < 1.0
    assert m.burn_rate("acme") < 1.0
    feed(True, 60)              # climbs back over 2.0: SECOND alert
    assert m.burn_rate("acme") >= 2.0
    assert n_alerts == 2


def test_burn_min_events_floor_and_unknown_tenant():
    m = BurnRateMonitor(budget=0.01, threshold=4.0, min_events=16)
    assert m.burn_rate("ghost") is None
    for i in range(15):         # one short of the floor: no rate yet
        assert m.note("acme", True, now=float(i)) is None
        assert m.burn_rate("acme", now=float(i)) is None
    assert m.note("acme", True, now=15.0) is not None   # floor met
    assert m.snapshot(now=15.0) == {"acme": pytest.approx(100.0 * 1.0)}


def test_burn_window_evicts():
    """Violations age out of the sliding window: a storm that ENDED
    stops burning."""
    m = BurnRateMonitor(budget=0.5, threshold=4.0, window_s=10.0,
                        min_events=2)
    for i in range(4):
        m.note("acme", True, now=float(i))
    assert m.burn_rate("acme", now=3.0) == pytest.approx(2.0)
    # 20s later the whole storm is outside the window
    m.note("acme", False, now=20.0)
    m.note("acme", False, now=21.0)
    assert m.burn_rate("acme", now=21.0) == pytest.approx(0.0)
    assert m.snapshot(now=40.0) == {"acme": None}   # window empty again


def test_burn_monitor_validates():
    with pytest.raises(ValueError, match="budget"):
        BurnRateMonitor(budget=0.0)
    with pytest.raises(ValueError, match="threshold"):
        BurnRateMonitor(threshold=-1.0)
