"""Memory-bounded redistribution synthesis (ISSUE 14).

The tentpole contracts under test:

* **synthesis** — a reshard whose EVERY single-shot route is pruned by
  ``hbm_limit`` now plans a chunked route (``Pipelined(chunks=K)``
  edges, verdict ``routed:hbm``) instead of falling back;
* **bit-identity** — chunked routes equal their unchunked siblings
  across (2,4)/(4,2)/(2,2) topologies × even/ragged extents × permuted
  index orders × ``wire_dtype=None|bf16`` (chunking along an
  exchange-untouched dim commutes with the exchange);
* **footprint model** — a hand-computed known-optimal case pins the
  time-sliced accounting (``elems*itemsize + chunk_elems*wire``) and
  the exact admission boundary: one byte below the chunked footprint
  and the search is exhausted;
* **donation pricing** — the pinned-source surcharge: ``donate=True``
  admits routes that non-donating pricing prunes at the same limit;
* **verification** — chunk-aware ``analysis.spmd.verify_hbm`` agrees
  with the planner byte-for-byte, and the compiled chunked chain's
  collective stats equal the priced schedule (HLO-pinned, count ×K);
* **end-to-end** — ``reshard(hbm_limit=)`` executes the synthesized
  route or fails typed; ``PencilFFTPlan(hbm_limit=)`` rewrites its own
  schedule the same way; ``serve/`` admits a previously-rejected whale
  request on the synthesized route with tenant isolation intact.
"""

import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    PencilFFTPlan,
    Permutation,
    Topology,
    gather,
    plan_reshard_route,
    reshard,
)
from pencilarrays_tpu.analysis import spmd
from pencilarrays_tpu.analysis.errors import HbmBoundError
from pencilarrays_tpu.obs import drift as obs_drift
from pencilarrays_tpu.parallel.routing import execute_route
from pencilarrays_tpu.parallel.transpositions import Pipelined

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(autouse=True)
def _clean_drift():
    obs_drift.drift_tracker.reset()
    yield
    obs_drift.drift_tracker.reset()


def _ref(shape, dtype=np.float32):
    n = int(np.prod(shape, dtype=int))
    return (np.arange(n, dtype=dtype).reshape(shape) + 1.0) / 3.0


def _tight_limit(pin, dest, dtype, wire=None):
    """A limit below the donated unconstrained route's peak — every
    single-shot edge is inadmissible under it."""
    method = AllToAll(wire_dtype=wire)
    un = plan_reshard_route(pin, dest, (), dtype, method=method,
                            donate=True)
    assert un.hops
    return un.peak_hbm_bytes - 1


# ---------------------------------------------------------------------------
# synthesis + bit-identity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dims", [(2, 4), (4, 2), (2, 2)])
@pytest.mark.parametrize("shape,perm_in,perm_out", [
    ((16, 12, 8), None, None),                       # even shards
    ((13, 10, 9), None, None),                       # ragged everywhere
    ((16, 12, 8), Permutation(2, 0, 1), Permutation(1, 2, 0)),
    ((13, 10, 9), Permutation(2, 0, 1), None),       # ragged + permuted
])
@pytest.mark.parametrize("wire", [None, "bf16"])
def test_chunked_route_bit_identity(devices, dims, shape, perm_in,
                                    perm_out, wire):
    """Chunked (hbm-synthesized) routes return bit-identical results to
    the unconstrained route across topologies, raggedness, permuted
    memory orders and wire formats."""
    topo = Topology(dims, devices=devices[: int(np.prod(dims))])
    pin = Pencil(topo, shape, (1, 2), permutation=perm_in)
    dest = Pencil(topo, shape, (0, 1), permutation=perm_out)
    method = AllToAll(wire_dtype=wire)
    un = plan_reshard_route(pin, dest, (), np.float32, method=method,
                            donate=True)
    lim = un.peak_hbm_bytes - 1
    plan = plan_reshard_route(pin, dest, (), np.float32, method=method,
                              hbm_limit=lim, donate=True)
    assert plan.use_route and plan.verdict == "routed:hbm"
    assert plan.peak_hbm_bytes <= lim < un.peak_hbm_bytes
    assert any(isinstance(h.method, Pipelined) for h in plan.hops), \
        "a limit below the single-shot peak must force chunking"
    x = PencilArray.from_global(pin, _ref(shape))
    out_un = execute_route(x, un)
    out_ch = execute_route(x, plan)
    np.testing.assert_array_equal(np.asarray(gather(out_ch)),
                                  np.asarray(gather(out_un)))
    # the chunk-aware verifier certifies the same accounting the
    # planner charged, byte-for-byte
    assert spmd.verify_hbm(plan, lim) == plan.peak_hbm_bytes


def test_chunked_route_hlo_pinned(devices):
    """The compiled chunked chain's collective stats equal the priced
    schedule op-for-op — count ×K, bytes unchanged."""
    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 1))
    lim = _tight_limit(pin, dest, np.float32)
    plan = plan_reshard_route(pin, dest, (), np.float32,
                              method=AllToAll(), hbm_limit=lim,
                              donate=True)
    assert any(isinstance(h.method, Pipelined) for h in plan.hops)
    trace = spmd.verify_route(plan, (), np.float32)
    # the chunked schedule genuinely multiplies collective launches
    total = sum(v["count"] for v in trace.stats().values())
    assert total == sum(v["count"] for h in plan.hops
                        for v in h.cost.values())
    assert total > len(plan.hops)


# ---------------------------------------------------------------------------
# hand-computed known-optimal case
# ---------------------------------------------------------------------------


def test_hand_computed_chunked_admission(devices):
    """(16,12,8) on a (2,4) mesh, (1,2)->(0,1), f32, donate=True.

    Every exchange operand holds 192 elements per chip (e.g. the first
    hop (1,2)->(0,2) exchanges the (16, 12/2, 8/4) block), so the
    single-shot footprint is ``192*4 + 192*4 = 1536`` bytes.  The
    first hop's only chunkable dim is the extent-2 trailing dim ->
    K=2 is the ONLY admissible slicing, with footprint
    ``192*4 + 96*4 = 1152``.  Under ``hbm_limit=1535`` only the
    chunked route exists; at 1151 the search must be exhausted."""
    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 1))

    un = plan_reshard_route(pin, dest, (), np.float32,
                            method=AllToAll(), donate=True)
    assert un.peak_hbm_bytes == 192 * 4 + 192 * 4 == 1536

    plan = plan_reshard_route(pin, dest, (), np.float32,
                              method=AllToAll(), hbm_limit=1535,
                              donate=True)
    assert plan.use_route and plan.verdict == "routed:hbm"
    assert [h.dest.decomposition for h in plan.hops] == [(0, 2), (0, 1)]
    assert [h.method.chunks for h in plan.hops] == [2, 2]
    assert plan.peak_hbm_bytes == 192 * 4 + 96 * 4 == 1152
    assert all(h.peak_hbm_bytes == 1152 for h in plan.hops)

    # exactly at the chunked footprint: admitted
    at = plan_reshard_route(pin, dest, (), np.float32,
                            method=AllToAll(), hbm_limit=1152,
                            donate=True)
    assert at.use_route and at.peak_hbm_bytes == 1152

    # one byte below, the 2-hop routes are exhausted (the (1,2)->(0,2)
    # edge's only chunkable dim has extent 2) and the planner DETOURS:
    # the 4-hop chain (1,0)->(2,0)->(2,1)->(0,1) trades hops for
    # deeper-chunkable edges — its worst edge is the final one, whose
    # chunk dim has extent 3 (192/3 * 1 = 64 elems per slice):
    # 192*4 + 64*4 = 1024 bytes.  That IS the graph's floor: at 1024
    # the detour is admitted, at 1023 the search is exhausted.
    below = plan_reshard_route(pin, dest, (), np.float32,
                               method=AllToAll(), hbm_limit=1151,
                               donate=True)
    assert below.use_route and len(below.hops) == 4
    assert [h.dest.decomposition for h in below.hops] == [
        (1, 0), (2, 0), (2, 1), (0, 1)]
    assert below.peak_hbm_bytes == 192 * 4 + 64 * 4 == 1024
    floor = plan_reshard_route(pin, dest, (), np.float32,
                               method=AllToAll(), hbm_limit=1024,
                               donate=True)
    assert floor.use_route and floor.peak_hbm_bytes == 1024
    exhausted = plan_reshard_route(pin, dest, (), np.float32,
                                   method=AllToAll(), hbm_limit=1023,
                                   donate=True)
    assert not exhausted.use_route
    assert exhausted.verdict == "gspmd:no-route"

    # wire interplay: under 1535 the bf16 edge fits SINGLE-SHOT
    # (192*4 + 192*2 = 1152 — the PR-13 packed-operand headroom), so
    # no chunking is synthesized; tighten below that and the in-flight
    # chunk is charged at its PACKED share (192*4 + 96*2 = 960)
    wired = plan_reshard_route(pin, dest, (), np.float32,
                               method=AllToAll(wire_dtype="bf16"),
                               hbm_limit=1535, donate=True)
    assert wired.use_route
    assert wired.peak_hbm_bytes == 192 * 4 + 192 * 2 == 1152
    assert not any(isinstance(h.method, Pipelined) for h in wired.hops)
    wired_tight = plan_reshard_route(pin, dest, (), np.float32,
                                     method=AllToAll(wire_dtype="bf16"),
                                     hbm_limit=1151, donate=True)
    assert wired_tight.use_route
    assert wired_tight.peak_hbm_bytes == 192 * 4 + 96 * 2 == 960
    assert [h.method.chunks for h in wired_tight.hops] == [2, 2]


def test_donation_is_part_of_edge_pricing(devices):
    """The pinned-source surcharge: a non-donated source block rides
    every edge's charge, so donate=True admits at limits donate=False
    prunes — and the static verifier reproduces both accountings."""
    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 1))
    S = pin.bytes_per_device((), np.float32)
    assert S == 192 * 4

    donated = plan_reshard_route(pin, dest, (), np.float32,
                                 method=AllToAll(), hbm_limit=1152,
                                 donate=True)
    assert donated.use_route
    kept = plan_reshard_route(pin, dest, (), np.float32,
                              method=AllToAll(), hbm_limit=1152,
                              donate=False)
    assert not kept.use_route, \
        "non-donating pricing must charge the resident source block"
    # at chunked-footprint + S the non-donating route is admitted, and
    # its per-hop charge is exactly the donated charge + S
    kept2 = plan_reshard_route(pin, dest, (), np.float32,
                               method=AllToAll(), hbm_limit=1152 + S,
                               donate=False)
    assert kept2.use_route
    assert kept2.peak_hbm_bytes == 1152 + S
    assert spmd.predicted_peak_hbm(kept2)[0] == 1152 + S
    assert spmd.predicted_peak_hbm(donated)[0] == 1152


# ---------------------------------------------------------------------------
# reshard() end-to-end
# ---------------------------------------------------------------------------


def test_reshard_hbm_limit_end_to_end(devices):
    topo = Topology((2, 4))
    shape = (16, 12, 8)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (0, 1))
    u = _ref(shape)
    baseline = np.asarray(gather(reshard(
        PencilArray.from_global(pin, u), dest, method=Gspmd())))

    # admissible with the pinned-source surcharge: 1152 + 768 = 1920
    out = reshard(PencilArray.from_global(pin, u), dest, hbm_limit=1920)
    np.testing.assert_array_equal(np.asarray(gather(out)), baseline)

    # donation buys the surcharge back: 1152 suffices with donate=True
    out2 = reshard(PencilArray.from_global(pin, u), dest,
                   hbm_limit=1152, donate=True)
    np.testing.assert_array_equal(np.asarray(gather(out2)), baseline)

    # below the graph floor (1024 donated): typed pre-flight error,
    # never an unbounded GSPMD fallback
    with pytest.raises(HbmBoundError):
        reshard(PencilArray.from_global(pin, u), dest, hbm_limit=1023,
                donate=True)
    # and Gspmd cannot be bounded at all
    with pytest.raises(ValueError, match="cannot bound"):
        reshard(PencilArray.from_global(pin, u), dest,
                method=Gspmd(), hbm_limit=1 << 30)


def test_route_plan_journal_carries_chunk_verdict(devices, tmp_path,
                                                  monkeypatch):
    """The ``route.plan`` record carries the synthesis verdict: chunk
    factors, per-hop footprints, the bound and the donation assumption
    (schema v4) — and lints clean."""
    from pencilarrays_tpu import obs
    from pencilarrays_tpu.obs import events as obs_events
    from pencilarrays_tpu.obs import metrics as obs_metrics

    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    try:
        topo = Topology((2, 4))
        shape = (16, 12, 8)
        pin = Pencil(topo, shape, (1, 2))
        dest = Pencil(topo, shape, (0, 1))
        x = PencilArray.from_global(pin, _ref(shape))
        reshard(x, dest, hbm_limit=1152, donate=True)
        events = obs.read_journal(jdir)
        assert obs.lint_journal(events) == []
        plans = [e for e in events if e["ev"] == "route.plan"]
        assert len(plans) == 1
        e = plans[0]
        assert e["verdict"] == "routed:hbm"
        assert e["hbm_limit"] == 1152 and e["donate"] is True
        assert e["peak_hbm_bytes"] == 1152
        routed = next(c for c in e["candidates"]
                      if c["kind"] == "routed")
        assert routed["chunks"] == [2, 2]
        assert routed["hop_peak_hbm_bytes"] == [1152, 1152]
    finally:
        obs_events._reset_for_tests()
        obs_metrics.registry.reset()


# ---------------------------------------------------------------------------
# PencilFFTPlan(hbm_limit=)
# ---------------------------------------------------------------------------


def test_fft_plan_hbm_limit_synthesizes_and_stays_bit_identical(devices):
    topo = Topology((2, 4))
    shape = (16, 12, 8)
    plan = PencilFFTPlan(topo, shape, real=True)
    peak, _ = spmd.predicted_peak_hbm(plan)
    bounded = PencilFFTPlan(topo, shape, real=True, hbm_limit=peak - 1)
    bpeak, _ = spmd.predicted_peak_hbm(bounded)
    assert bpeak <= peak - 1
    assert spmd.verify_hbm(bounded, peak - 1) == bpeak
    # at least one hop gained a Pipelined override
    assert any(len(s) > 4 and isinstance(s[4], Pipelined)
               for s in bounded._steps if s[0] == "t")
    # prediction == compiled schedule, both directions, chunking priced
    spmd.verify_plan(bounded, (), "forward")
    spmd.verify_plan(bounded, (), "backward")
    # bit-identity + distinct fingerprints (serve coalescing must never
    # mix bounded and unbounded executables)
    u = _ref(shape)
    a = np.asarray(gather(plan.forward(
        PencilArray.from_global(plan.input_pencil, u))))
    b = np.asarray(gather(bounded.forward(
        PencilArray.from_global(bounded.input_pencil, u))))
    np.testing.assert_array_equal(a, b)
    assert plan.plan_key() != bounded.plan_key()


def test_fft_plan_hbm_limit_rechunks_fused_hops(devices):
    topo = Topology((2, 4))
    shape = (16, 12, 8)
    plan = PencilFFTPlan(topo, shape, real=True, pipeline=2)
    peak, _ = spmd.predicted_peak_hbm(plan)
    bounded = PencilFFTPlan(topo, shape, real=True, pipeline=2,
                            hbm_limit=peak - 1)
    assert spmd.predicted_peak_hbm(bounded)[0] <= peak - 1
    # the fused steps' own bounds grew; schedule still verifies
    k_before = [len(s[9]) for s in plan._steps if s[0] == "ft"]
    k_after = [len(s[9]) for s in bounded._steps if s[0] == "ft"]
    assert k_after and max(k_after) > max(k_before)
    spmd.verify_plan(bounded, (), "forward")
    u = _ref(shape)
    a = np.asarray(gather(plan.forward(
        PencilArray.from_global(plan.input_pencil, u))))
    b = np.asarray(gather(bounded.forward(
        PencilArray.from_global(bounded.input_pencil, u))))
    np.testing.assert_array_equal(a, b)


def test_fft_plan_hbm_limit_impossible_is_typed(devices):
    topo = Topology((2, 4))
    with pytest.raises(HbmBoundError, match="hop"):
        PencilFFTPlan(topo, (16, 12, 8), real=True, hbm_limit=64)
    with pytest.raises(ValueError, match="hbm_limit"):
        PencilFFTPlan(topo, (16, 12, 8), real=True, hbm_limit=0)


# ---------------------------------------------------------------------------
# serve: whale admission
# ---------------------------------------------------------------------------


def test_serve_admits_whale_via_synthesized_route(devices):
    """A reshard whose every single-shot route busts the service's
    ``hbm_limit`` is admitted on the synthesized chunked route and
    served correctly — with another tenant's FFT traffic riding the
    same service untouched (tenant isolation intact)."""
    from pencilarrays_tpu.serve import PlanService

    topo = Topology((2, 4))
    shape = (16, 12, 8)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (0, 1))
    # 1920 = chunked footprint (1152) + pinned source (768): below the
    # 2304 single-shot charge, so only the synthesized route fits
    svc = PlanService(max_batch=1, hbm_limit=1920)
    try:
        u = _ref(shape)
        x = PencilArray.from_global(pin, u)
        t_whale = svc.submit_reshard("whale", x, dest)
        plan = PencilFFTPlan(topo, shape, real=True)
        t_small = svc.submit("small", _ref(shape), plan=plan)
        svc.drain()
        got = np.asarray(gather(t_whale.result(timeout=60)))
        ref = np.asarray(gather(reshard(x, dest, method=Gspmd())))
        np.testing.assert_array_equal(got, ref)
        # the small tenant's transform is untouched by the whale
        small = t_small.result(timeout=60)
        exp = np.asarray(gather(plan.forward(
            PencilArray.from_global(plan.input_pencil, u))))
        np.testing.assert_allclose(np.asarray(gather(small)), exp,
                                   rtol=1e-5, atol=1e-5)
    finally:
        svc.close()


def test_serve_rejects_infeasible_whale_typed(devices):
    from pencilarrays_tpu.serve import PlanService
    from pencilarrays_tpu.serve.errors import AdmissionError

    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 1))
    # the non-donated graph floor is 1024 + the 768-byte pinned source
    # = 1792; one byte under it nothing is admissible
    svc = PlanService(max_batch=1, hbm_limit=1791)
    try:
        x = PencilArray.from_global(pin, _ref((16, 12, 8)))
        with pytest.raises(AdmissionError) as ei:
            svc.submit_reshard("whale", x, dest)
        assert ei.value.reason == "hbm-limit"
        assert svc.queue.depth() == 0   # never entered the queue
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# bench smoke (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_hbm_sweep_smoke(devices):
    """The benchmark's hbm-limit arm runs end to end on a small config
    and reports at least one synthesized (chunked) point with a clean
    verify_hbm verdict and bit-identity."""
    from benchmarks.reshard_sweep import measure_hbm_sweep

    topo = Topology((2, 4))
    points = measure_hbm_sweep(topo, (16, 12, 8), k1=2, repeats=1)
    routed = [p for p in points if p.get("verdict") == "routed:hbm"]
    assert routed
    assert all(p["verify_hbm_ok"] for p in routed)
    assert all(p["bit_identical"] for p in routed)
    assert all(max(p["chunks"]) > 1 for p in routed)
    # the sweep terminates at the floor with an exhausted search
    assert points[-1]["verdict"] in ("gspmd:no-route",) or routed


def test_serve_hbm_whales_do_not_coalesce(devices):
    """Two individually-admissible whales must not stack into one
    batch whose doubled footprint floor busts the bound at dispatch
    (review finding): hbm-bounded reshards serve one per batch, and
    both results are correct."""
    from pencilarrays_tpu.serve import PlanService

    topo = Topology((2, 4))
    shape = (16, 12, 8)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (0, 1))
    svc = PlanService(max_batch=8, max_wait_s=10.0, hbm_limit=1920)
    try:
        u1, u2 = _ref(shape), _ref(shape) + 1.0
        t1 = svc.submit_reshard("a", PencilArray.from_global(pin, u1),
                                dest)
        t2 = svc.submit_reshard("b", PencilArray.from_global(pin, u2),
                                dest)
        assert t1.key != t2.key     # solo coalesce keys
        svc.drain()
        np.testing.assert_array_equal(
            np.asarray(gather(t1.result(60))), u1)
        np.testing.assert_array_equal(
            np.asarray(gather(t2.result(60))), u2)
        assert svc.stats()["dispatches"] == 2
    finally:
        svc.close()


def test_reshard_hbm_raise_leaves_no_phantom_dispatch_metric(
        devices, tmp_path, monkeypatch):
    """The typed HbmBoundError path dispatches nothing — and must not
    count a reshard.dispatches{path=gspmd} (review finding)."""
    from pencilarrays_tpu import obs
    from pencilarrays_tpu.obs import events as obs_events
    from pencilarrays_tpu.obs import metrics as obs_metrics

    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    try:
        topo = Topology((2, 4))
        pin = Pencil(topo, (16, 12, 8), (1, 2))
        dest = Pencil(topo, (16, 12, 8), (0, 1))
        x = PencilArray.from_global(pin, _ref((16, 12, 8)))
        with pytest.raises(HbmBoundError):
            reshard(x, dest, hbm_limit=1023, donate=True)
        snap = obs.snapshot()
        assert not any(k.startswith("reshard.dispatches")
                       for k in snap["counters"]), snap["counters"]
    finally:
        obs_events._reset_for_tests()
        obs_metrics.registry.reset()


def test_fft_plan_hbm_limit_accepts_numpy_int(devices):
    topo = Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 8), real=True)
    peak, _ = spmd.predicted_peak_hbm(plan)
    b = PencilFFTPlan(topo, (16, 12, 8), real=True,
                      hbm_limit=np.int64(peak - 1))
    assert b.hbm_limit == peak - 1
    assert spmd.predicted_peak_hbm(b)[0] <= peak - 1
    with pytest.raises(ValueError):
        PencilFFTPlan(topo, (16, 12, 8), real=True, hbm_limit=True)
