"""Resilience subsystem tests: crash-safe checksummed checkpoints,
deterministic fault injection, retry/backoff, and the truncation fuzz —
the single-process half of the failure-path story (the SIGKILL
subprocess drills live in ``test_multiprocess.py``).

The load-bearing property, asserted by the fuzz test: a corrupted
checkpoint NEVER yields garbage data — every failure surfaces as a
typed :class:`ResilienceError`, and ``latest_valid()`` falls back to an
older intact checkpoint or ``None``."""

import json
import os
import shutil

import numpy as np
import pytest

from pencilarrays_tpu import Pencil, PencilArray, Permutation, Topology, gather
from pencilarrays_tpu.io import BinaryDriver, HDF5Driver, has_hdf5, open_file
from pencilarrays_tpu.parallel import distributed
from pencilarrays_tpu.resilience import (
    CheckpointManager,
    CheckpointNotFoundError,
    CorruptCheckpointError,
    CorruptSidecarError,
    InjectedFault,
    ResilienceError,
    RetryDeadlineExceeded,
    RetryPolicy,
    faults,
)

pytestmark = pytest.mark.chaos


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


@pytest.fixture
def pen(topo):
    return Pencil(topo, (11, 13, 10), (1, 2), permutation=Permutation(2, 0, 1))


def make_data(pen, extra=(), seed=0, dtype=np.float64):
    shape = pen.size_global() + extra
    u = np.random.default_rng(seed).standard_normal(shape).astype(dtype)
    return u, PencilArray.from_global(pen, u)


# -- faults ----------------------------------------------------------------
def test_fault_spec_parsing():
    r, = faults.parse("io.write_block:torn@3")
    assert (r.point, r.mode, r.times, r.first) == ("io.write_block", "torn",
                                                   1, 3)
    r1, r2 = faults.parse("dist.initialize:error*3, barrier:kill@2")
    assert (r1.mode, r1.times, r1.first) == ("error", 3, 1)
    assert (r2.mode, r2.times, r2.first) == ("kill", 1, 2)
    with pytest.raises(ValueError, match="unknown injection point"):
        faults.parse("io.wrte_block:error")
    with pytest.raises(ValueError, match="mode"):
        faults.parse("barrier:explode")


def test_fault_counters_are_deterministic():
    with faults.active("io.flush_meta:error*2@2"):
        faults.fire("io.flush_meta")  # hit 1: passes
        for _ in range(2):            # hits 2-3: trigger
            with pytest.raises(InjectedFault):
                faults.fire("io.flush_meta")
        faults.fire("io.flush_meta")  # hit 4: exhausted, passes
        faults.fire("io.open")        # other points untouched
    faults.fire("io.flush_meta")      # rules cleared


def test_injected_fault_is_transient_oserror():
    from pencilarrays_tpu.resilience import is_transient

    with faults.active("barrier:error"):
        with pytest.raises(InjectedFault) as ei:
            distributed.sync_global_devices("probe")
    assert isinstance(ei.value, OSError)
    assert isinstance(ei.value, ResilienceError)
    assert is_transient(ei.value)


def test_fault_env_rearm(monkeypatch):
    """The env spec is re-read when it changes — a worker can arm itself
    after import (the killwrite phase relies on this)."""
    monkeypatch.setenv(faults.ENV_VAR, "io.open:error")
    with pytest.raises(InjectedFault):
        faults.fire("io.open")
    monkeypatch.setenv(faults.ENV_VAR, "")
    faults.fire("io.open")


# -- retry -----------------------------------------------------------------
def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("not up yet")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.001, deadline=5.0)
    assert policy.call(flaky, label="flaky") == "ok"
    assert len(calls) == 3


def test_retry_does_not_touch_nontransient():
    def boom():
        raise FileNotFoundError("missing is not transient")

    with pytest.raises(FileNotFoundError):
        RetryPolicy(max_attempts=5, base_delay=0.001).call(boom)


def test_retry_deadline_exceeded():
    def always():
        raise ConnectionError("down")

    policy = RetryPolicy(max_attempts=100, base_delay=0.2, max_delay=0.2,
                         deadline=0.05)
    with pytest.raises(RetryDeadlineExceeded) as ei:
        policy.call(always, label="down-service")
    assert isinstance(ei.value.__cause__, ConnectionError)


def test_retry_exhausts_attempts_reraises_original():
    def always():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        RetryPolicy(max_attempts=3, base_delay=0.001).call(always)


def test_retry_policy_env_knobs(monkeypatch):
    monkeypatch.setenv("PENCILARRAYS_TPU_RETRIES", "7")
    monkeypatch.setenv("PENCILARRAYS_TPU_RETRY_DEADLINE", "1.5")
    p = RetryPolicy.from_env()
    assert p.max_attempts == 7 and p.deadline == 1.5


# -- distributed guards ----------------------------------------------------
def test_initialize_retries_transient_then_succeeds(monkeypatch):
    """``dist.initialize`` under an injected transient failure succeeds
    within the retry deadline instead of crashing (acceptance
    criterion)."""
    import jax

    connected = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: connected.append(a))
    monkeypatch.setattr(distributed, "_initialized", False)
    policy = RetryPolicy(max_attempts=10, base_delay=0.001, deadline=10.0)
    with faults.active("dist.initialize:error*3"):
        distributed.initialize("127.0.0.1:1", 1, 0, retry=policy)
    assert len(connected) == 1
    assert distributed.is_initialized()
    # double-init is a clear error up front, not an opaque jax failure
    with pytest.raises(RuntimeError, match="ensure_initialized"):
        distributed.initialize("127.0.0.1:1", 1, 0)
    # ...and the idempotent path is a no-op
    assert distributed.ensure_initialized("127.0.0.1:1", 1, 0) is False


def test_initialize_deadline_bounds_persistent_failure(monkeypatch):
    import jax

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: None)
    monkeypatch.setattr(distributed, "_initialized", False)
    policy = RetryPolicy(max_attempts=100, base_delay=0.2, max_delay=0.2,
                         deadline=0.05)
    with faults.active("dist.initialize:error"):
        with pytest.raises(RetryDeadlineExceeded):
            distributed.initialize("127.0.0.1:1", 1, 0, retry=policy)
    assert not distributed._initialized  # only set on success


def test_initialize_retry_resets_partial_jax_state(monkeypatch):
    """jax's State.initialize creates client/service BEFORE connect();
    a failed connect leaves them set, and without a rollback every
    retry would die on jax's 'should only be called once' guard while
    is_initialized() lied.  Simulate that exact state machine."""
    import jax

    class FakeHandle:
        def __init__(self):
            self.shut = False

        def shutdown(self):
            self.shut = True

    class FakeState:
        client = None
        service = None
        preemption_sync_manager = None
        coordinator_address = None

    state = FakeState()
    attempts = []

    def fake_init(*a, **k):
        if state.client is not None:
            raise RuntimeError(
                "distributed.initialize should only be called once.")
        state.client = FakeHandle()  # set BEFORE the connect...
        state.service = FakeHandle()
        attempts.append(1)
        if len(attempts) < 3:
            raise RuntimeError(
                "timed out connecting to coordinator")  # ...which fails

    monkeypatch.setattr(jax.distributed, "global_state", state,
                        raising=False)
    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(distributed, "_initialized", False)
    fast = RetryPolicy(max_attempts=5, base_delay=0.001, deadline=5.0)
    distributed.initialize("127.0.0.1:1", 2, 0, retry=fast)
    assert len(attempts) == 3
    assert distributed.is_initialized()
    assert state.client is not None  # the successful connection survives


def test_initialize_runtime_error_classification(monkeypatch):
    """Transient-looking RuntimeErrors from jax (coordinator not up yet)
    are retried; config errors fail fast on the first attempt."""
    import jax

    calls = []

    def flaky(*a, **k):
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError(
                "DEADLINE_EXCEEDED: timed out connecting to coordinator")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(distributed, "_initialized", False)
    fast = RetryPolicy(max_attempts=5, base_delay=0.001, deadline=5.0)
    distributed.initialize("127.0.0.1:1", 1, 0, retry=fast)
    assert len(calls) == 3

    bad_calls = []

    def bad(*a, **k):
        bad_calls.append(1)
        raise RuntimeError("process_id 7 out of range")

    monkeypatch.setattr(jax.distributed, "initialize", bad)
    monkeypatch.setattr(distributed, "_initialized", False)
    with pytest.raises(RuntimeError, match="out of range"):
        distributed.initialize("127.0.0.1:1", 1, 0, retry=fast)
    assert len(bad_calls) == 1  # no useless backoff on a config error


def test_ensure_initialized_single_process_noop():
    assert distributed.ensure_initialized(None, num_processes=1,
                                          process_id=0) is False
    assert distributed.ensure_initialized() is False


def test_ensure_initialized_autodetects_pod_env(monkeypatch):
    """On a Cloud TPU pod (metadata env markers present) the
    argument-less ensure_initialized still runs the auto-detected
    bootstrap instead of silently acting single-process."""
    import jax

    connected = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: connected.append(a))
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    assert distributed.ensure_initialized() is True
    assert len(connected) == 1
    # explicit single-process stays a no-op even on a pod machine
    monkeypatch.setattr(distributed, "_initialized", False)
    assert distributed.ensure_initialized(num_processes=1) is False


# -- corrupt sidecar (satellite) -------------------------------------------
def test_corrupt_sidecar_is_typed_error(tmp_path, pen):
    u, x = make_data(pen)
    path = str(tmp_path / "data.bin")
    with open_file(BinaryDriver(), path, write=True, create=True) as f:
        f.write("u", x)
    with open(path + ".json", "w") as f:
        f.write('{"datasets": [{"name": "u", "off')  # truncated mid-JSON
    with pytest.raises(CorruptSidecarError, match="latest_valid"):
        open_file(BinaryDriver(), path, read=True).__enter__()


# -- checkpoint manager ----------------------------------------------------
def test_checkpoint_roundtrip_and_layout(tmp_path, pen, topo):
    u, x = make_data(pen, seed=1)
    v, y = make_data(pen, extra=(2,), seed=2)
    mgr = CheckpointManager(str(tmp_path), keep=4)
    p = mgr.save(7, {"u": x, "v": y})
    assert sorted(os.listdir(p)) == ["COMMIT", "MANIFEST.json", "data.bin",
                                     "data.bin.json"]
    with open(os.path.join(p, "MANIFEST.json")) as f:
        mf = json.load(f)
    assert mf["step"] == 7 and mf["driver"] == "BinaryDriver"
    assert set(mf["datasets"]) == {"u", "v"}
    blocks = mf["datasets"]["u"]["blocks"]
    assert blocks and all({"start", "shape", "crc"} <= set(b) for b in blocks)
    # blocks tile the global array exactly
    assert sum(int(np.prod(b["shape"])) for b in blocks) == u.size

    mgr.verify(7)
    assert mgr.latest_valid() == 7
    ck = mgr.restore()
    assert ck.datasets == ["u", "v"]
    # restore under different decompositions (the drivers' contract)
    pen2 = Pencil(topo, (11, 13, 10), (0, 1))
    pen3 = Pencil(Topology((8,)), (11, 13, 10), (1,))
    np.testing.assert_array_equal(gather(ck.read("u", pen2)), u)
    np.testing.assert_array_equal(gather(ck.read("v", pen3)), v)


def test_checkpoint_collections(tmp_path, pen, topo):
    fields = [make_data(pen, seed=20 + i) for i in range(3)]
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"state": tuple(x for _, x in fields)})
    pen2 = Pencil(topo, (11, 13, 10), (0, 2))
    back = mgr.restore().read("state", pen2)
    assert isinstance(back, tuple) and len(back) == 3
    for (u, _), b in zip(fields, back):
        np.testing.assert_array_equal(gather(b), u)


def test_checkpoint_retention_gc(tmp_path, pen):
    _, x = make_data(pen)
    mgr = CheckpointManager(str(tmp_path), keep=2)
    for step in (1, 2, 3, 4):
        mgr.save(step, {"u": x})
    assert mgr.steps() == [3, 4]
    assert sorted(os.listdir(tmp_path)) == ["step-00000003", "step-00000004"]


def test_checkpoint_uncommitted_is_skipped(tmp_path, pen):
    u, x = make_data(pen, seed=3)
    w, z = make_data(pen, seed=4)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"u": x})
    p2 = mgr.save(2, {"u": z})
    os.unlink(os.path.join(p2, "COMMIT"))  # simulate crash-before-commit
    assert mgr.latest_valid() == 1
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen)), u)
    with pytest.raises(CheckpointNotFoundError):
        mgr.restore(2)
    # ...and the next save's GC sweeps the torn directory
    mgr.save(3, {"u": x})
    assert not os.path.exists(p2)


def test_resave_same_step_never_destroys_committed_copy(tmp_path, pen):
    """Re-saving an existing committed step moves the old directory
    aside instead of deleting it, so no crash window destroys the only
    copy; a clean re-save replaces the content and leaves no debris."""
    u, x = make_data(pen, seed=16)
    v, y = make_data(pen, seed=17)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"u": x})
    mgr.save(1, {"u": y})  # clean replace
    assert mgr.steps() == [1]
    assert sorted(os.listdir(tmp_path)) == ["step-00000001"]
    np.testing.assert_array_equal(gather(mgr.restore(1).read("u", pen)), v)


def test_unknown_manifest_algo_degrades_not_fails(tmp_path, pen):
    """A checkpoint whose checksum algorithm this host cannot compute is
    NOT falsely failed: verification degrades to structural checks."""
    u, x = make_data(pen, seed=18)
    mgr = CheckpointManager(str(tmp_path))
    p = mgr.save(1, {"u": x})
    mpath = os.path.join(p, "MANIFEST.json")
    with open(mpath) as f:
        mf = json.load(f)
    mf["algo"] = "crc64-nvme"  # written by some future host
    with open(mpath, "w") as f:
        json.dump(mf, f)
    mgr.verify(1)  # structural only, no false CorruptCheckpointError
    assert mgr.latest_valid() == 1
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen)), u)


def test_checkpoint_crash_before_commit_fault(tmp_path, pen):
    """``ckpt.commit:error`` aborts the save between manifest flush and
    rename: the temp directory never becomes visible and the previous
    checkpoint survives."""
    u, x = make_data(pen, seed=5)
    _, z = make_data(pen, seed=6)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"u": x})
    with faults.active("ckpt.commit:error"):
        with pytest.raises(InjectedFault):
            mgr.save(2, {"u": z})
    assert mgr.latest_valid() == 1
    assert not os.path.exists(mgr._step_dir(2))
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen)), u)


def test_checkpoint_transient_flush_faults_are_retried(tmp_path, pen):
    """A transient error at the sidecar flush and at the driver open is
    absorbed by the retry policy — the save/restore still succeeds."""
    u, x = make_data(pen, seed=7)
    fast = RetryPolicy(max_attempts=5, base_delay=0.001, deadline=5.0)
    mgr = CheckpointManager(str(tmp_path), retry=fast)
    with faults.active("io.flush_meta:error*1, io.open:error*1"):
        mgr.save(1, {"u": x})
    assert mgr.latest_valid() == 1
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen)), u)


def test_checkpoint_corruption_names_dataset_and_block(tmp_path, pen):
    u, x = make_data(pen, seed=8)
    v, y = make_data(pen, seed=9)
    mgr = CheckpointManager(str(tmp_path))
    p = mgr.save(1, {"u": x, "v": y})
    with open(os.path.join(p, "data.bin.json")) as f:
        d = next(d for d in json.load(f)["datasets"] if d["name"] == "v")
    with open(os.path.join(p, "data.bin"), "r+b") as f:
        f.seek(d["offset_bytes"] + 128)
        b = f.read(1)
        f.seek(d["offset_bytes"] + 128)
        f.write(bytes([b[0] ^ 0x01]))
    with pytest.raises(CorruptCheckpointError, match=r"'v' block \d+"):
        mgr.verify(1)
    try:
        mgr.verify(1)
    except CorruptCheckpointError as e:
        assert e.dataset == "v" and e.block is not None and e.step == 1
    # the reader refuses to hand out the corrupt dataset...
    with pytest.raises(CorruptCheckpointError):
        mgr.restore(1).read("v", pen)
    # ...but verification is per-dataset: the intact one still restores
    np.testing.assert_array_equal(gather(mgr.restore(1).read("u", pen)), u)
    assert mgr.latest_valid() is None
    with pytest.raises(CheckpointNotFoundError):
        mgr.restore()


def test_checkpoint_hdf5_driver(tmp_path, pen, topo):
    if not has_hdf5():
        pytest.skip("h5py unavailable")
    u, x = make_data(pen, seed=10)
    mgr = CheckpointManager(str(tmp_path), driver=HDF5Driver())
    p = mgr.save(1, {"u": x})
    assert os.path.exists(os.path.join(p, "data.h5"))
    mgr.verify(1)
    pen2 = Pencil(topo, (11, 13, 10), (0, 1))
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen2)), u)
    # flip one byte inside the dataset's storage (h5py exposes the
    # contiguous dataset's file offset)
    import h5py

    with h5py.File(os.path.join(p, "data.h5"), "r") as h:
        off = h["u"].id.get_offset()
    assert off is not None
    with open(os.path.join(p, "data.h5"), "r+b") as f:
        f.seek(off + 40)
        b = f.read(1)
        f.seek(off + 40)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(ResilienceError):
        mgr.verify(1)


def test_checkpoint_checksums_off(tmp_path, pen):
    u, x = make_data(pen, seed=11)
    mgr = CheckpointManager(str(tmp_path), checksums=False)
    p = mgr.save(1, {"u": x})
    with open(os.path.join(p, "MANIFEST.json")) as f:
        mf = json.load(f)
    assert mf["algo"] is None and mf["datasets"]["u"]["blocks"] is None
    assert mgr.latest_valid() == 1  # commit + metadata checks still apply
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen)), u)
    # a silent bit flip is the documented cost of checksums=False: the
    # manager still refuses STRUCTURALLY broken checkpoints (sidecar)
    with open(os.path.join(p, "data.bin.json"), "w") as f:
        f.write("{not json")
    assert mgr.latest_valid() is None


def test_checksums_off_validates_chunks_and_orbax_layouts(tmp_path, pen):
    """Checksums-off verification is structural only and must accept
    layouts the block reader cannot describe: a chunks-layout binary
    checkpoint and an Orbax checkpoint both verify and restore."""
    from pencilarrays_tpu.io import OrbaxDriver, has_orbax

    u, x = make_data(pen, seed=21)
    mgr = CheckpointManager(str(tmp_path / "ck"), checksums=False)
    mgr.save(0, {"u": x}, chunks=True)
    assert mgr.latest_valid() == 0
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen)), u)
    if has_orbax():
        mgro = CheckpointManager(str(tmp_path / "cko"),
                                 driver=OrbaxDriver(), checksums=False)
        mgro.save(0, {"u": x})
        assert mgro.latest_valid() == 0
        np.testing.assert_array_equal(
            gather(mgro.restore().read("u", pen)), u)


def test_interrupted_resave_is_recovered(tmp_path, pen):
    """Simulate a crash between moving the old committed step aside and
    committing its replacement: latest_valid() recovers the moved-aside
    copy instead of losing the step (and GC must not sweep it)."""
    u, x = make_data(pen, seed=22)
    _, y = make_data(pen, seed=23)
    mgr = CheckpointManager(str(tmp_path), keep=1)
    p = mgr.save(5, {"u": x})
    # crash mid-re-save: old dir parked in the -replaced namespace, torn
    # replacement present without COMMIT
    os.rename(p, str(tmp_path / ".tmp-step-00000005-replaced"))
    os.makedirs(p)
    with open(os.path.join(p, "data.bin"), "wb") as f:
        f.write(b"torn")
    assert mgr.latest_valid() == 5  # recovered, not lost
    np.testing.assert_array_equal(gather(mgr.restore(5).read("u", pen)), u)
    mgr.save(6, {"u": y})  # next save's GC leaves the recovered world sane
    assert mgr.steps() == [6]  # keep=1


def test_checkpoint_rejects_bad_configs(tmp_path, pen):
    from pencilarrays_tpu.io import OrbaxDriver

    _, x = make_data(pen)
    with pytest.raises(ValueError, match="checksums"):
        CheckpointManager(str(tmp_path), driver=OrbaxDriver())
    mgr = CheckpointManager(str(tmp_path))
    with pytest.raises(ValueError, match="chunks"):
        mgr.save(1, {"u": x}, chunks=True)
    if has_hdf5():
        mgr_h = CheckpointManager(str(tmp_path), driver=HDF5Driver(),
                                  checksums=False)
        with pytest.raises(ValueError, match="BinaryDriver layout"):
            mgr_h.save(1, {"u": x}, chunks=True)
    with pytest.raises(ValueError, match="empty"):
        mgr.save(1, {})
    with pytest.raises(CheckpointNotFoundError):
        mgr.restore()


# -- the truncation/corruption fuzz ---------------------------------------
def test_truncation_fuzz_never_returns_garbage(tmp_path, pen):
    """Truncate/corrupt checkpoint files at seeded random offsets: every
    outcome is either a bit-identical restore of an INTACT checkpoint or
    a typed ResilienceError — never silently wrong data (acceptance
    criterion)."""
    u, x = make_data(pen, seed=12)
    pristine = str(tmp_path / "pristine")
    mgr0 = CheckpointManager(pristine, keep=1)
    mgr0.save(1, {"u": x})

    rng = np.random.default_rng(2026)
    targets = ["data.bin", "data.bin.json", "MANIFEST.json", "COMMIT"]
    outcomes = {"restored": 0, "typed_error": 0}
    for trial in range(24):
        work = str(tmp_path / f"fuzz{trial}")
        shutil.copytree(os.path.join(pristine, "step-00000001"),
                        os.path.join(work, "step-00000001"))
        victim = os.path.join(work, "step-00000001",
                              targets[trial % len(targets)])
        size = os.path.getsize(victim)
        mode = ["truncate", "flip", "zero"][trial % 3]
        with open(victim, "r+b") as f:
            if mode == "truncate" or size == 0:
                f.truncate(int(rng.integers(0, max(size, 1))))
            else:
                off = int(rng.integers(0, size))
                f.seek(off)
                b = f.read(1) or b"\0"
                f.seek(off)
                f.write(bytes([b[0] ^ (0xFF if mode == "flip" else b[0])]))
        mgr = CheckpointManager(work, keep=1)
        step = mgr.latest_valid()
        if step is None:
            outcomes["typed_error"] += 1
            continue
        try:
            back = mgr.restore(step).read("u", pen)
        except ResilienceError:
            outcomes["typed_error"] += 1
            continue
        # whatever survived validation MUST be the true data
        np.testing.assert_array_equal(gather(back), u)
        outcomes["restored"] += 1
    # both outcomes must actually occur: corruption is detected AND
    # benign damage (e.g. inside COMMIT's content) still restores
    assert outcomes["typed_error"] > 0
    assert outcomes["restored"] > 0


def test_fuzz_older_checkpoint_fallback(tmp_path, pen):
    """Corrupting the newest checkpoint makes ``latest_valid`` fall back
    to the older intact one, and the restore is bit-identical."""
    u1, x1 = make_data(pen, seed=13)
    u2, x2 = make_data(pen, seed=14)
    mgr = CheckpointManager(str(tmp_path), keep=5)
    mgr.save(1, {"u": x1})
    p2 = mgr.save(2, {"u": x2})
    with open(os.path.join(p2, "data.bin"), "r+b") as f:
        f.truncate(os.path.getsize(os.path.join(p2, "data.bin")) // 2)
    assert mgr.latest_valid() == 1
    np.testing.assert_array_equal(gather(mgr.restore().read("u", pen)), u1)


# -- checksum plumbing -----------------------------------------------------
def test_blocks_stream_through_observer_without_extra_copy(pen):
    """The manifest CRCs come from the write path's own block streaming:
    the observer sees exactly the logical-order blocks iter_local_blocks
    yields, and their CRCs match an independent full-array computation
    per block."""
    from pencilarrays_tpu.io.binary import iter_local_blocks
    from pencilarrays_tpu.resilience.checksum import (BlockChecksums,
                                                      crc_of_array)

    u, x = make_data(pen, seed=15)
    crcs = BlockChecksums()
    obs = crcs.observer("u")
    for start, block in iter_local_blocks(x):
        obs(start, block)
    blocks = crcs.blocks("u")
    assert sum(int(np.prod(b["shape"])) for b in blocks) == u.size
    for b in blocks:
        sl = tuple(slice(s, s + e) for s, e in zip(b["start"], b["shape"]))
        assert crc_of_array(u[sl]) == b["crc"]


# -- cross-decomposition restore (ISSUE 8) ---------------------------------
def _tear_byte(step_dir):
    path = os.path.join(step_dir, "data.bin")
    with open(path, "r+b") as f:
        f.seek(64)
        b = f.read(1)
        f.seek(64)
        f.write(bytes([b[0] ^ 0xFF]))


def _reader_pencils(devices, shape):
    """Three (writer-layout -> reader-layout) targets, including a
    world-size change: same 4 devices re-decomposed (4,1), a 2-device
    mesh, and a single device (``world == 1`` — the post-reformation
    shape of the 2-rank elastic drill)."""
    return [
        Pencil(Topology((4, 1), devices=devices[:4]), shape, (1, 2)),
        Pencil(Topology((1, 2), devices=devices[:2]), shape, (0, 1),
               permutation=Permutation(2, 0, 1)),
        Pencil(Topology((1,), devices=devices[:1]), shape, (2,)),
    ]


def test_cross_decomposition_restore_bit_identical(tmp_path, devices):
    """A checkpoint written on a (2,2) decomposition restores onto
    (4,1), (1,2) and world=1 bit-identically, with full checksum
    verification AND the local-extent mode — the manifest keys blocks
    by logical-order global corner, so the reader's decomposition (and
    device count) is free to differ from the writer's."""
    shape = (11, 13, 10)
    truth = np.random.default_rng(21).standard_normal(shape)
    pen_w = Pencil(Topology((2, 2), devices=devices[:4]), shape, (1, 2))
    mgr = CheckpointManager(str(tmp_path), keep=4)
    mgr.save(1, {"u": PencilArray.from_global(pen_w, truth)})
    for pen_r in _reader_pencils(devices, shape):
        ck = mgr.restore(1)
        back = ck.read("u", pen_r, verify=True)
        np.testing.assert_array_equal(gather(back), truth)
        back = ck.read("u", pen_r, verify="local")
        np.testing.assert_array_equal(gather(back), truth)


def test_cross_decomposition_restore_skips_torn_step(tmp_path, devices):
    """Torn-step skipping is preserved across a decomposition change:
    the newest step's data file is corrupted, so ``latest_valid()``
    falls back to step 1 and THAT restores cleanly onto every reader
    layout — while explicitly reading the torn step 2 raises a typed
    checksum failure, never garbage."""
    shape = (11, 13, 10)
    truth = np.random.default_rng(22).standard_normal(shape)
    pen_w = Pencil(Topology((2, 2), devices=devices[:4]), shape, (1, 2))
    mgr = CheckpointManager(str(tmp_path), keep=4)
    mgr.save(1, {"u": PencilArray.from_global(pen_w, truth)})
    mgr.save(2, {"u": PencilArray.from_global(pen_w, truth + 5.0)})
    _tear_byte(os.path.join(str(tmp_path), "step-00000002"))
    assert mgr.latest_valid() == 1
    for pen_r in _reader_pencils(devices, shape):
        back = mgr.restore(1).read("u", pen_r, verify=True)
        np.testing.assert_array_equal(gather(back), truth)
        with pytest.raises(CorruptCheckpointError):
            mgr.restore(2, verify=False).read("u", pen_r, verify="local")


def test_local_verify_blocks_intersection():
    """The pure mapping behind ``verify="local"``: only manifest blocks
    overlapping the reader's local extents are selected."""
    blocks = [
        {"start": [0, 0, 0], "shape": [4, 4, 8], "crc": 1},
        {"start": [0, 4, 0], "shape": [4, 4, 8], "crc": 2},
        {"start": [4, 0, 0], "shape": [4, 4, 8], "crc": 3},
        {"start": [4, 4, 0], "shape": [4, 4, 8], "crc": 4},
    ]
    # reader rank owning rows 0..3 only: the two row-0 blocks intersect
    picked = CheckpointManager._blocks_intersecting(
        [(range(0, 4), range(0, 8), range(0, 8))], 3, blocks)
    assert [b["crc"] for b in picked] == [1, 2]
    # a rank owning a column slab crossing both row groups
    picked = CheckpointManager._blocks_intersecting(
        [(range(0, 8), range(2, 6), range(0, 8))], 3, blocks)
    assert [b["crc"] for b in picked] == [1, 2, 3, 4]
    # empty extents pick nothing
    assert CheckpointManager._blocks_intersecting(
        [(range(0, 0), range(0, 8), range(0, 8))], 3, blocks) == []
