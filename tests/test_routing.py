"""Reshard route planner + whole-plan fused executables (ISSUE 4).

Pins the tentpole contracts:

* routed multi-slot reshard is BIT-identical to the GSPMD result
  (padding included) across topologies, uneven shards and permuted
  memory orders;
* the planner returns the known-optimal route on hand-built pencil
  graphs, and drift-tracker samples steer its edge weights;
* each routed hop keeps its HLO-pinned collective budget (the chain's
  compiled program contains exactly the predicted collectives);
* ``Auto`` never executes a route the model prices worse than GSPMD,
  and the verdict is journaled as a schema-clean ``route.plan`` event;
* ``PencilFFTPlan.compile()`` is one dispatch per direction,
  bit-identical to the eager hop-by-hop schedule;
* GSPMD hops are priced from their partitioned HLO
  (``gspmd_reshard_cost``), so the baseline comparison is real.
"""

import re

import jax
import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    PencilFFTPlan,
    Permutation,
    Topology,
    gather,
    gspmd_reshard_cost,
    plan_reshard_route,
    reshard,
)
from pencilarrays_tpu.analysis import spmd
from pencilarrays_tpu.obs import drift as obs_drift
from pencilarrays_tpu.parallel import routing
from pencilarrays_tpu.parallel import transpositions as tr


@pytest.fixture(autouse=True)
def _clean_drift():
    """Route plans are drift-sensitive: isolate every test's samples."""
    obs_drift.drift_tracker.reset()
    yield
    obs_drift.drift_tracker.reset()


def global_ref(shape, dtype=np.float64):
    n = int(np.prod(shape, dtype=int))
    return (np.arange(n, dtype=dtype).reshape(shape) + 1.0) / 3.0


# ---------------------------------------------------------------------------
# bit-identity: routed chain vs GSPMD, >= 3 topologies
# ---------------------------------------------------------------------------


TOPO_CASES = [
    # (topo dims, n devices) — M=2 meshes so multi-slot reshards exist
    ((2, 4), 8),
    ((4, 2), 8),
    ((2, 2), 4),
]


@pytest.mark.parametrize("dims,n", TOPO_CASES)
@pytest.mark.parametrize("shape", [(16, 12, 8), (13, 10, 9)])
def test_routed_bit_identical_to_gspmd(devices, dims, n, shape):
    """Every topology x (even | uneven) shape, with permuted memory
    orders on both ends: the routed fused chain and the one GSPMD
    exchange must produce the same backing array BIT-for-bit (padding
    included)."""
    topo = Topology(dims, devices=jax.devices()[:n])
    u = global_ref(shape)
    pin = Pencil(topo, shape, (1, 2), permutation=Permutation(2, 0, 1))
    dest = Pencil(topo, shape, (0, 1), permutation=Permutation(1, 2, 0))
    x = PencilArray.from_global(pin, u)
    plan = plan_reshard_route(pin, dest, (), x.dtype)
    assert plan.hops, "expected an admissible route on an M=2 mesh"
    y_routed = routing.execute_route(x, plan)
    y_gspmd = reshard(x, dest, method=Gspmd())
    np.testing.assert_array_equal(np.asarray(y_routed.data),
                                  np.asarray(y_gspmd.data))
    np.testing.assert_array_equal(gather(y_routed), u)


@pytest.mark.parametrize("dims,n", TOPO_CASES)
def test_default_reshard_matches_gspmd(devices, dims, n):
    """The public reshard() (planner-routed by default) stays
    bit-identical to the forced GSPMD path whatever the verdict."""
    topo = Topology(dims, devices=jax.devices()[:n])
    shape = (11, 9, 14)
    u = global_ref(shape)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (0, 1), permutation=Permutation(2, 0, 1))
    x = PencilArray.from_global(pin, u)
    y = reshard(x, dest)
    y_ref = reshard(x, dest, method=Gspmd())
    np.testing.assert_array_equal(np.asarray(y.data), np.asarray(y_ref.data))
    np.testing.assert_array_equal(gather(y), u)


def test_slot_swap_routes(devices):
    """A slot swap ((1,2) -> (2,1)) has no single-slot shortcut; the
    planner must chain through intermediates and stay exact."""
    topo = Topology((2, 4))
    shape = (10, 12, 8)
    u = global_ref(shape)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (2, 1))
    x = PencilArray.from_global(pin, u)
    plan = plan_reshard_route(pin, dest, (), x.dtype)
    assert len(plan.hops) >= 2
    y = routing.execute_route(x, plan)
    np.testing.assert_array_equal(gather(y), u)
    np.testing.assert_array_equal(
        np.asarray(y.data),
        np.asarray(reshard(x, dest, method=Gspmd()).data))


def test_fully_decomposed_falls_back(devices):
    """M == N leaves no single-slot moves (every logical dim is
    sharded): the search is exhausted and reshard() falls back to
    GSPMD — the pre-planner capability is never lost."""
    topo = Topology((2, 4))
    shape = (8, 12)
    pin = Pencil(topo, shape, (0, 1))
    dest = Pencil(topo, shape, (1, 0))
    plan = plan_reshard_route(pin, dest, (), np.float32)
    assert not plan.hops and not plan.use_route
    assert plan.verdict == "gspmd:no-route"
    u = global_ref(shape)
    y = reshard(PencilArray.from_global(pin, u), dest)
    np.testing.assert_array_equal(gather(y), u)


# ---------------------------------------------------------------------------
# planner unit tests: known-optimal routes on hand-built graphs
# ---------------------------------------------------------------------------


def test_single_slot_route_is_direct(devices):
    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 2))
    plan = plan_reshard_route(pin, dest, (), np.float32)
    assert [h.dest.decomposition for h in plan.hops] == [(0, 2)]


def test_two_hop_route_unique_path(devices):
    """(1,2) -> (0,1) on N=3: the only 2-hop chain goes via (0,2), and
    3-hop detours cost strictly more wire bytes."""
    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 1))
    plan = plan_reshard_route(pin, dest, (), np.float32)
    assert [h.dest.decomposition for h in plan.hops] == [(0, 2), (0, 1)]


def test_planner_picks_cheaper_of_two_routes(devices):
    """N=4 (2,3) -> (0,1) has two 2-hop chains: via (0,3) or via (2,1).
    With shape (9, 8, 6, 4) the (0,3) leg pays dim-0 tail padding
    (9 -> 10) on BOTH hops while the (2,1) leg pays it once — hand
    computation: 240+240 vs 216+240 operand elements — so the planner
    must route via (2,1)."""
    topo = Topology((2, 4))
    shape = (9, 8, 6, 4)
    pin = Pencil(topo, shape, (2, 3))
    dest = Pencil(topo, shape, (0, 1))
    plan = plan_reshard_route(pin, dest, (), np.float32)
    assert [h.dest.decomposition for h in plan.hops] == [(2, 1), (0, 1)]
    # and the hand-computed byte totals hold (f32)
    assert sum(v["bytes"] for h in plan.hops
               for v in h.cost.values()) == (216 + 240) * 4


def test_drift_samples_steer_the_route(devices):
    """The PR-3 drift tracker corrects edge weights: a trusted timing
    sample showing the (2,3)->(2,1) exchange running far over its byte
    model (and another showing (2,3)->(0,3) under it) must flip the
    planned route onto the un-drifted path."""
    topo = Topology((2, 4))
    shape = (9, 8, 6, 4)
    pin = Pencil(topo, shape, (2, 3))
    dest = Pencil(topo, shape, (0, 1))
    via_21 = Pencil(topo, shape, (2, 1))
    via_03 = Pencil(topo, shape, (0, 3))
    # baseline: the cheaper-bytes route via (2,1) wins
    plan = plan_reshard_route(pin, dest, (), np.float32)
    assert [h.dest.decomposition for h in plan.hops] == [(2, 1), (0, 1)]
    # poison the (2,3)->(2,1) edge: measured 1s for its 864 bytes, while
    # (2,3)->(0,3) moves 960 bytes in ~0s — the fitted bandwidth makes
    # the poisoned edge's drift huge and the other's tiny
    obs_drift.drift_tracker.record(
        tr._hop_label(pin, via_21, AllToAll(), np.float32),
        216 * 4, 1.0, source="benchtime")
    obs_drift.drift_tracker.record(
        tr._hop_label(pin, via_03, AllToAll(), np.float32),
        240 * 4, 1e-7, source="benchtime")
    plan2 = plan_reshard_route(pin, dest, (), np.float32)
    assert [h.dest.decomposition for h in plan2.hops] == [(0, 3), (0, 1)]


def test_explicit_method_forces_routed_path(devices):
    """An explicit exchange method is a user decision: the planner must
    execute it on every edge (verdict routed:forced, no GSPMD baseline
    substitution), and the compiled chain must contain that method's
    collectives."""
    topo = Topology((2, 4))
    shape = (16, 12, 8)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (0, 1))
    plan = plan_reshard_route(pin, dest, (), np.float32,
                              method=pa.Ring())
    assert plan.verdict == "routed:forced" and plan.use_route
    assert all(isinstance(h.method, pa.Ring) for h in plan.hops)
    u = global_ref(shape)
    x = PencilArray.from_global(pin, u)
    y = reshard(x, dest, method=pa.Ring())
    np.testing.assert_array_equal(gather(y), u)
    np.testing.assert_array_equal(
        np.asarray(y.data),
        np.asarray(reshard(x, dest, method=Gspmd()).data))


def test_dispatch_samples_do_not_steer_or_invalidate(devices):
    """Per-dispatch wall times are lower bounds on wire time: they must
    neither flip routes nor churn the plan cache (the trusted-sample
    contract of DriftTracker.version())."""
    topo = Topology((2, 4))
    shape = (9, 8, 6, 4)
    pin = Pencil(topo, shape, (2, 3))
    dest = Pencil(topo, shape, (0, 1))
    via_21 = Pencil(topo, shape, (2, 1))
    plan = plan_reshard_route(pin, dest, (), np.float32)
    v0 = obs_drift.drift_tracker.version()
    # a wildly slow DISPATCH sample on the winning edge: ignored
    obs_drift.drift_tracker.record(
        tr._hop_label(pin, via_21, AllToAll(), np.float32),
        216 * 4, 10.0, source="dispatch")
    assert obs_drift.drift_tracker.version() == v0
    plan2 = plan_reshard_route(pin, dest, (), np.float32)
    assert plan2 is plan  # same cached object: no replanning churn
    assert [h.dest.decomposition for h in plan2.hops] == [(2, 1), (0, 1)]


def test_hbm_limit_prunes_routes(devices):
    """A peak-HBM bound below any hop's operand+result footprint leaves
    no admissible route -> GSPMD fallback."""
    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 1))
    plan = plan_reshard_route(pin, dest, (), np.float32, hbm_limit=1)
    assert not plan.hops and plan.verdict == "gspmd:no-route"
    wide = plan_reshard_route(pin, dest, (), np.float32, hbm_limit=2 ** 40)
    assert wide.hops and wide.peak_hbm_bytes <= 2 ** 40


def test_route_never_priced_worse_than_gspmd(devices):
    """The acceptance rule: use_route implies the routed score is
    strictly cheaper than the priced GSPMD baseline."""
    topo = Topology((2, 4))
    for shape, perm in [((16, 12, 8), None), ((13, 10, 9),
                                              Permutation(2, 0, 1))]:
        pin = Pencil(topo, shape, (1, 2), permutation=perm)
        dest = Pencil(topo, shape, (0, 1))
        plan = plan_reshard_route(pin, dest, (), np.float32)
        if plan.use_route and plan.gspmd_score_bytes is not None:
            assert plan.score_bytes < plan.gspmd_score_bytes
        if (not plan.use_route and plan.hops
                and plan.gspmd_score_bytes is not None):
            assert plan.score_bytes >= plan.gspmd_score_bytes


# ---------------------------------------------------------------------------
# HLO-pinned collective budget of the routed chain
# ---------------------------------------------------------------------------


def test_routed_chain_hlo_budget(devices):
    """The compiled fused chain contains EXACTLY the collectives the
    per-hop byte model predicts — count and bytes (the transpose-engine
    validation, extended over a whole route, through the ONE shared
    extractor: ``analysis.spmd``)."""
    topo = Topology((2, 4))
    shape = (16, 12, 8)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (0, 1))
    plan = plan_reshard_route(pin, dest, (), np.float32)
    assert plan.hops
    expect: dict = {}
    for h in plan.hops:
        for op, c in h.cost.items():
            e = expect.setdefault(op, {"count": 0, "bytes": 0})
            e["count"] += c["count"]
            e["bytes"] += c["bytes"]
    # verify_route raises a typed ScheduleMismatchError naming the op
    # on divergence; the stats equality keeps the original pin exact
    trace = spmd.verify_route(plan, (), np.float32)
    assert trace.stats() == expect


# ---------------------------------------------------------------------------
# GSPMD pricing (satellite: transpositions.py Gspmd hops)
# ---------------------------------------------------------------------------


def test_gspmd_reshard_cost_prices_collectives(devices):
    topo = Topology((2, 4))
    pin = Pencil(topo, (16, 12, 8), (1, 2))
    dest = Pencil(topo, (16, 12, 8), (0, 1))
    cost = gspmd_reshard_cost(pin, dest, (), np.float32)
    assert cost, "a two-slot reshard must move bytes"
    assert sum(v["bytes"] for v in cost.values()) > 0
    assert all(v["count"] >= 1 for v in cost.values())


def test_transpose_cost_gspmd_matches_compiled(devices):
    """Single-slot Gspmd hops are priced too (no more skipping), and
    the price equals the compiled transpose's measured collectives."""
    topo = Topology((4,), devices=jax.devices()[:4])
    pin = Pencil(topo, (8, 8), (0,))
    pout = Pencil(topo, (8, 8), (1,))
    cost = pa.transpose_cost(pin, pout, method=Gspmd())
    assert spmd.trace_transpose(pin, pout, (), np.float32,
                                Gspmd()).stats() == cost
    assert sum(v["bytes"] for v in cost.values()) > 0


# ---------------------------------------------------------------------------
# route.plan journaling
# ---------------------------------------------------------------------------


def test_route_plan_event_journaled(devices, tmp_path, monkeypatch):
    from pencilarrays_tpu import obs
    from pencilarrays_tpu.obs import events as obs_events
    from pencilarrays_tpu.obs import metrics as obs_metrics

    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    try:
        topo = Topology((2, 4))
        shape = (16, 12, 8)
        pin = Pencil(topo, shape, (1, 2))
        dest = Pencil(topo, shape, (0, 1))
        x = PencilArray.from_global(pin, global_ref(shape))
        reshard(x, dest)
        reshard(x, dest)  # dedup: one verdict per (run, config)
        events = obs.read_journal(jdir)
        assert obs.lint_journal(events) == []
        plans = [e for e in events if e["ev"] == "route.plan"]
        assert len(plans) == 1
        e = plans[0]
        assert e["verdict"] in ("routed", "gspmd", "gspmd:no-route",
                                "gspmd:unpriced")
        kinds = {c["kind"] for c in e["candidates"]}
        assert "routed" in kinds
        routed = next(c for c in e["candidates"] if c["kind"] == "routed")
        assert routed["predicted_bytes"] > 0
        if e["verdict"] == "routed" and "gspmd" in kinds:
            gs = next(c for c in e["candidates"] if c["kind"] == "gspmd")
            assert routed["score_bytes"] < gs["score_bytes"]
        # executable-cache counters surfaced in the snapshot (satellite)
        snap = obs.snapshot()
        assert any(k.startswith("compile.cache") or
                   k.startswith("reshard.dispatches")
                   for k in snap["counters"]), snap["counters"]
    finally:
        obs_events._reset_for_tests()
        obs_metrics.registry.reset()


# ---------------------------------------------------------------------------
# reshard donate + whole-plan compile()
# ---------------------------------------------------------------------------


def test_reshard_donate_api(devices):
    """donate=True stays correct on both the routed and the GSPMD
    path (buffer invalidation itself is backend-dependent; the contract
    under test is correctness + a distinct donating executable)."""
    topo = Topology((2, 4))
    shape = (12, 10, 14)
    u = global_ref(shape)
    pin = Pencil(topo, shape, (1, 2))
    dest = Pencil(topo, shape, (0, 1), permutation=Permutation(2, 0, 1))
    for method in (None, Gspmd()):
        x = PencilArray.from_global(pin, u)
        kwargs = {} if method is None else {"method": method}
        y = reshard(x, dest, donate=True, **kwargs)
        np.testing.assert_array_equal(gather(y), u)


def test_plan_compile_bit_identical_and_single_dispatch(devices):
    """compile() executes the full chain bit-identically to the eager
    schedule, and after the first (tracing) call the eager interpreter
    is never re-entered — one executable dispatch per direction."""
    topo = Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=np.float64)
    u = PencilArray.from_global(
        plan.input_pencil,
        np.random.default_rng(7).standard_normal((16, 12, 10)))
    uh_eager = plan.forward(u)
    back_eager = plan.backward(uh_eager)

    compiled = plan.compile()
    assert plan.compile() is compiled  # cached per (extra_dims, donate)

    calls = {"fwd": 0, "bwd": 0}
    orig_fwd, orig_bwd = plan.forward, plan.backward
    plan.forward = lambda *a, **k: (calls.__setitem__(
        "fwd", calls["fwd"] + 1), orig_fwd(*a, **k))[1]
    plan.backward = lambda *a, **k: (calls.__setitem__(
        "bwd", calls["bwd"] + 1), orig_bwd(*a, **k))[1]
    try:
        uh_c = compiled.forward(u)       # traces once
        back_c = compiled.backward(uh_c)
        assert calls == {"fwd": 1, "bwd": 1}
        for _ in range(3):               # pure executable dispatches
            uh_c = compiled.forward(u)
            back_c = compiled.backward(uh_c)
        assert calls == {"fwd": 1, "bwd": 1}, (
            "compiled plan re-entered the eager per-hop interpreter")
    finally:
        del plan.forward, plan.backward
    np.testing.assert_array_equal(np.asarray(uh_c.data),
                                  np.asarray(uh_eager.data))
    np.testing.assert_array_equal(np.asarray(back_c.data),
                                  np.asarray(back_eager.data))


def test_plan_compile_validates_inputs(devices):
    topo = Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True)
    compiled = plan.compile()
    wrong = PencilArray.zeros(Pencil(topo, (16, 12, 10), (0, 2)),
                              dtype=plan.dtype_physical)
    with pytest.raises(ValueError, match="input_pencil"):
        compiled.forward(wrong)
    with pytest.raises(ValueError, match="extra_dims"):
        compiled.forward(plan.allocate_input((3,)))


def test_plan_compile_extra_dims_and_pipeline(devices):
    """Batch dims and fused pipelined hops ride through the one-program
    path unchanged."""
    topo = Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=np.float64,
                         pipeline=2)
    u = PencilArray.from_global(
        plan.input_pencil,
        np.random.default_rng(8).standard_normal((16, 12, 10, 3)))
    assert u.extra_dims == (3,)
    compiled = plan.compile((3,))
    uh_eager = plan.forward(u)
    uh_c = compiled.forward(u)
    np.testing.assert_array_equal(np.asarray(uh_c.data),
                                  np.asarray(uh_eager.data))


def test_many_pencil_reshard_to(devices):
    """ManyPencilArray.reshard_to jumps non-adjacent configurations in
    one routed dispatch, landing on the same data transpose_to reaches
    hop by hop."""
    from pencilarrays_tpu import ManyPencilArray

    topo = Topology((2, 4))
    shape = (12, 10, 8)
    u = global_ref(shape)
    pens = [Pencil(topo, shape, d) for d in [(1, 2), (0, 2), (0, 1)]]
    a = ManyPencilArray(*pens, first=PencilArray.from_global(pens[0], u))
    b = ManyPencilArray(*pens, first=PencilArray.from_global(pens[0], u))
    a.reshard_to(2, donate=False)
    b.transpose_to(2, donate=False)
    assert a.index == b.index == 2
    np.testing.assert_array_equal(np.asarray(a.current.data),
                                  np.asarray(b.current.data))
    np.testing.assert_array_equal(gather(a.current), u)


# ---------------------------------------------------------------------------
# persistent compilation cache knob (satellite)
# ---------------------------------------------------------------------------


def test_compile_cache_env_knob(tmp_path, monkeypatch):
    from pencilarrays_tpu.utils.jaxcompat import (COMPILE_CACHE_VAR,
                                                  configure_compilation_cache)

    old = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv(COMPILE_CACHE_VAR, raising=False)
        assert configure_compilation_cache() is None
        monkeypatch.setenv(COMPILE_CACHE_VAR, str(tmp_path / "cc"))
        got = configure_compilation_cache()
        assert got == str(tmp_path / "cc")
        assert jax.config.jax_compilation_cache_dir == got
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


# ---------------------------------------------------------------------------
# sweep smoke (opt-in CI arm)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_reshard_sweep_smoke(devices):
    from benchmarks.reshard_sweep import measure_reshards

    topo = Topology((2, 4))
    points = measure_reshards(topo, (12, 10, 8), k1=3, repeats=2)
    assert len(points) == 3
    for p in points:
        assert p["gspmd_seconds"] > 0
        if p["route"] is not None:
            assert p["routed_seconds"] > 0
            assert p["routed_predicted_bytes"] > 0
