"""Multi-tenant plan service (serve/): registry, coalescing, quotas,
cost ordering, tenant isolation, elastic rebind.

The contracts under test (ISSUE 10 acceptance):

* ``plan_key()`` is deterministic across processes (subprocess-pinned)
  and provably agrees with the obs journal's ``plan_fp``;
* the registry shares ONE executable per fingerprint across tenants,
  counts hits/misses under ``cache="serve"`` with a per-tenant
  dimension, and never double-counts against the plan-level
  ``cache="plan"`` counters;
* N concurrent same-plan requests coalesce into batched dispatches
  (ragged final batch included) answered BIT-IDENTICALLY to N
  sequential ``plan.compile()(x)`` calls, across c2c/r2c × fwd/bwd;
* per-tenant quotas reject at admission with typed
  ``AdmissionError``; mixed-plan traffic dispatches cheapest-first
  (``collective_costs``-priced) with an anti-starvation override;
* the tenant-isolation drill: an injected SDC on one tenant's hop
  (``hop.exchange:corrupt``) raises typed ``IntegrityError`` on THAT
  tenant's tickets while the other tenant's queued requests complete
  bit-identically to an unfaulted run — full lifecycle journaled,
  lint-clean, rendered by the real ``pa-obs`` CLI;
* a named plan's elastic rebuild swaps the registry entry and the
  queued host-payload requests re-bind and drain.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import guard, obs
from pencilarrays_tpu.guard import IntegrityError
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.resilience import RetryPolicy, faults
from pencilarrays_tpu.serve import (
    AdmissionError,
    PlanRegistry,
    PlanService,
    ServeError,
    ServiceClosedError,
    StaleRequestError,
    TenantQuota,
)

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Serve tests touch obs, guard and faults: start (and leave)
    everything disabled and reset."""
    for var in (obs.ENV_VAR, guard.ENV_VAR, faults.ENV_VAR,
                "PENCILARRAYS_TPU_RETRIES"):
        monkeypatch.delenv(var, raising=False)
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    guard._reset_for_tests()
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _topo2(devices):
    return pa.Topology((2,), devices=devices[:2])


def _host(rng, shape, real=False):
    if real:
        return rng.standard_normal(shape).astype(np.float32)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


def _np(x):
    return np.asarray(pa.gather(x))


# ---------------------------------------------------------------------------
# plan_key: the public stable fingerprint
# ---------------------------------------------------------------------------


def test_plan_key_stable_and_dtype_sensitive(devices):
    topo = _topo2(devices)
    a = PencilFFTPlan(topo, (8, 6, 4), transforms=("rfft", "fft", "fft"))
    b = PencilFFTPlan(topo, (8, 6, 4), transforms=("rfft", "fft", "fft"))
    assert a.plan_key() == b.plan_key()
    assert len(a.plan_key()) == 12
    assert a.plan_key() == a._fingerprint()
    # every configuration knob must feed the key
    c = PencilFFTPlan(topo, (8, 6, 4), transform="fft")
    assert c.plan_key() != a.plan_key()
    # single-device plans have no exchange steps: the explicit dtype
    # field is what keeps f32 and f64 inputs distinct
    t1 = pa.Topology((1,), devices=devices[:1])
    d32 = PencilFFTPlan(t1, (8, 6), transform="dct", dtype=np.float32)
    d64 = PencilFFTPlan(t1, (8, 6), transform="dct", dtype=np.float64)
    assert d32.plan_key() != d64.plan_key()


def test_plan_key_deterministic_in_subprocess(devices):
    """Same inputs -> same key in a FRESH process (registry keys must
    survive jax restarts; nothing identity- or device-bound may leak
    into the hash)."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4),
                         transforms=("rfft", "fft", "fft"), pipeline=2)
    script = (
        "import jax\n"
        "import pencilarrays_tpu as pa\n"
        "from pencilarrays_tpu.ops.fft import PencilFFTPlan\n"
        "topo = pa.Topology((2,), devices=jax.devices()[:2])\n"
        "p = PencilFFTPlan(topo, (8, 6, 4),\n"
        "                  transforms=('rfft', 'fft', 'fft'), pipeline=2)\n"
        "print('KEY=' + p.plan_key())\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    assert f"KEY={plan.plan_key()}" in out.stdout, (out.stdout,
                                                    plan.plan_key())


def test_plan_key_agrees_with_journal_plan_fp(devices, tmp_path):
    """The registry key IS the obs correlation fingerprint: a plan's
    ``plan.build`` record carries plan_fp == plan_key()."""
    obs.enable(str(tmp_path / "obs"))
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    events = obs_events.read_journal(str(tmp_path / "obs"))
    builds = [e for e in events if e["ev"] == "plan.build"]
    assert builds and builds[-1]["plan_fp"] == plan.plan_key()
    obs.disable()


def test_reshard_key_stable(devices):
    from pencilarrays_tpu.parallel.routing import reshard_key

    topo = pa.Topology((2, 2), devices=devices[:4])
    src = pa.Pencil(topo, (8, 6, 4), (1, 2))
    dst = pa.Pencil(topo, (8, 6, 4), (0, 2))
    k1 = reshard_key(src, dst, np.float32)
    src2 = pa.Pencil(topo, (8, 6, 4), (1, 2))
    assert reshard_key(src2, dst, np.float32) == k1
    assert reshard_key(src, dst, np.complex64) != k1
    assert reshard_key(dst, src, np.float32) != k1


# ---------------------------------------------------------------------------
# registry: shared executables + serve-labeled cache counters
# ---------------------------------------------------------------------------


def test_registry_dedupes_plans_and_counts_per_tenant(devices, tmp_path):
    obs.enable(str(tmp_path / "obs"))
    topo = _topo2(devices)
    p1 = PencilFFTPlan(topo, (8, 6, 4))
    p2 = PencilFFTPlan(topo, (8, 6, 4))   # a second tenant's equal plan
    reg = PlanRegistry()
    assert reg.register(p1) is p1
    assert reg.register(p2) is p1         # fingerprint dedupe
    cp = reg.compiled(p1, (), tenants=["alice"])
    assert reg.compiled(p2, (), tenants=["alice", "bob"]) is cp
    st = reg.stats()
    assert (st["hits"], st["misses"]) == (1, 1)
    counters = obs_metrics.snapshot()["counters"]
    assert counters[
        "compile.cache_misses{cache=serve,tenant=alice}"] == 1
    assert counters["compile.cache_hits{cache=serve,tenant=alice}"] == 1
    assert counters["compile.cache_hits{cache=serve,tenant=bob}"] == 1
    # the double-count fix: the registry's resolve must NOT also tick
    # the plan-level counters...
    assert not any("cache=plan" in k for k in counters)
    # ...which keep counting DIRECT plan.compile() callers
    p1.compile(())
    counters = obs_metrics.snapshot()["counters"]
    assert counters["compile.cache_hits{cache=plan}"] == 1
    obs.disable()


def test_registry_replace_drops_stale_executables(devices):
    topo = _topo2(devices)
    p1 = PencilFFTPlan(topo, (8, 6, 4))
    reg = PlanRegistry()
    reg.register(p1)
    reg.compiled(p1, ())
    assert reg.stats()["executables"] == 1
    p2 = PencilFFTPlan(topo, (8, 6, 4))   # rebuilt (same fingerprint)
    assert reg.register(p2, replace=True) is p2
    assert reg.stats()["executables"] == 0, \
        "a rebuilt plan's key must not serve the dead plan's executable"


# ---------------------------------------------------------------------------
# coalescing correctness: batched == sequential, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("real", [False, True], ids=["c2c", "r2c"])
@pytest.mark.parametrize("direction", ["forward", "backward"])
def test_coalesced_equals_sequential(devices, real, direction):
    """5 concurrent same-plan requests through a max_batch=4 service
    (one full + one RAGGED batch) are answered bit-identically to 5
    sequential ``plan.compile()(x)`` calls."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4), real=real)
    rng = np.random.default_rng(7)
    if direction == "forward":
        us = [_host(rng, plan.shape_physical, real=real) for _ in range(5)]
    else:
        # physical spectra: forward images of random fields (a backward
        # request's payload lives on the output pencil / spectral dtype)
        cp0 = plan.compile(())
        us = [_np(cp0.forward(pa.PencilArray.from_global(
            plan.input_pencil, _host(rng, plan.shape_physical, real=real))))
            for _ in range(5)]
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    tickets = [svc.submit("t0" if i % 2 else "t1", u, plan=plan,
                          direction=direction)
               for i, u in enumerate(us)]
    assert svc.drain() == 2     # one full batch of 4 + the ragged 1
    cp = plan.compile(())
    pen = plan.input_pencil if direction == "forward" else plan.output_pencil
    dt = (plan.dtype_physical if direction == "forward"
          else plan.dtype_spectral)
    for u, t in zip(us, tickets):
        x = pa.PencilArray.from_global(pen, np.asarray(u, dt))
        ref = cp.forward(x) if direction == "forward" else cp.backward(x)
        assert np.array_equal(_np(t.result(5)), _np(ref)), \
            "coalesced dispatch is not bit-identical to sequential"
    st = svc.stats()
    assert st["completed"] == {"ok": 5}
    assert st["dispatches"] == 2


def test_pencilarray_payloads_and_cache_reuse(devices):
    """Device-array payloads work; a second wave of traffic reuses the
    resident executable (registry hit, no recompile)."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(3)
    svc = PlanService(max_batch=2, max_wait_s=0.0)
    for wave in range(2):
        us = [pa.PencilArray.from_global(
            plan.input_pencil, _host(rng, plan.shape_physical))
            for _ in range(2)]
        ts = [svc.submit("t", u, plan=plan) for u in us]
        svc.drain()
        cp = plan.compile(())
        for u, t in zip(us, ts):
            assert np.array_equal(_np(t.result(5)), _np(cp.forward(u)))
    st = svc.stats()["registry"]
    assert st["misses"] == 1 and st["hits"] == 1


def test_reshard_requests_coalesce_bit_identically(devices):
    topo = pa.Topology((2, 2), devices=devices[:4])
    src = pa.Pencil(topo, (8, 6, 4), (1, 2))
    dst = pa.Pencil(topo, (8, 6, 4), (0, 2))
    rng = np.random.default_rng(5)
    us = [pa.PencilArray.from_global(src, _host(rng, (8, 6, 4)))
          for _ in range(3)]
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    ts = [svc.submit_reshard("t", u, dst) for u in us]
    assert svc.drain() == 1     # ONE coalesced reshard dispatch
    for u, t in zip(us, ts):
        out = t.result(5)
        assert out.pencil == dst
        assert np.array_equal(_np(out), _np(pa.reshard(u, dst)))


# ---------------------------------------------------------------------------
# admission + ordering
# ---------------------------------------------------------------------------


def test_admission_quotas_typed_and_released(devices, tmp_path):
    obs.enable(str(tmp_path / "obs"))
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(0)
    u = _host(rng, (8, 6, 4))
    svc = PlanService(max_batch=8, max_wait_s=60.0,
                      quotas={"small": TenantQuota(max_requests=2),
                              "thin": TenantQuota(max_bytes=100)})
    svc.submit("small", u, plan=plan)
    svc.submit("small", u, plan=plan)
    with pytest.raises(AdmissionError) as ei:
        svc.submit("small", u, plan=plan)
    assert ei.value.tenant == "small"
    assert ei.value.reason == "queue-depth"
    with pytest.raises(AdmissionError) as ei:
        svc.submit("thin", u, plan=plan)
    assert ei.value.reason == "inflight-bytes"
    # other tenants are untouched by one tenant's quota pressure
    svc.submit("big", u, plan=plan)
    svc.drain()
    # completion releases the quota: the tenant can submit again
    svc.submit("small", u, plan=plan)
    svc.drain()
    counters = obs_metrics.snapshot()["counters"]
    assert counters["serve.rejected{reason=queue-depth,tenant=small}"] == 1
    assert counters["serve.rejected{reason=inflight-bytes,tenant=thin}"] == 1
    obs.disable()


def test_cost_ordering_small_before_big(devices, tmp_path):
    """Mixed-plan traffic dispatches cheapest-first: a small tenant's
    request submitted AFTER a huge plan's batch still dispatches first
    (collective_costs pricing), and the anti-starvation override flips
    the order back to FIFO once the big batch is old enough."""
    obs.enable(str(tmp_path / "obs"))
    topo = _topo2(devices)
    big = PencilFFTPlan(topo, (24, 16, 12))
    small = PencilFFTPlan(topo, (6, 4, 4))
    rng = np.random.default_rng(1)
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    tb = svc.submit("heavy", _host(rng, (24, 16, 12)), plan=big)
    ts = svc.submit("light", _host(rng, (6, 4, 4)), plan=small)
    svc.drain()
    assert ts.t_done is not None and tb.t_done is not None
    dispatches = [e for e in obs_events.read_journal(str(tmp_path / "obs"))
                  if e["ev"] == "serve.dispatch"]
    assert [d["key"] for d in dispatches] == [ts.key, tb.key]
    assert dispatches[0]["score_bytes"] < dispatches[1]["score_bytes"]
    obs.disable()
    # starve_after_s=0: every batch counts as starved -> admission order
    svc2 = PlanService(max_batch=4, max_wait_s=0.0, starve_after_s=0.0)
    b2 = svc2.queue
    tb2 = svc2.submit("heavy", _host(rng, (24, 16, 12)), plan=big)
    ts2 = svc2.submit("light", _host(rng, (6, 4, 4)), plan=small)
    ready = b2.take_ready(flush=True)
    assert [b.key for b in ready] == [tb2.key, ts2.key]
    for b in ready:
        svc2._dispatch(b)


def test_single_sample_contract_and_close(devices):
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    svc = PlanService()
    with pytest.raises(ServeError, match="single-sample"):
        svc.submit("t", pa.PencilArray.zeros(plan.input_pencil, (2,),
                                             plan.dtype_physical),
                   plan=plan)
    svc.close()
    with pytest.raises(ServiceClosedError):
        svc.submit("t", np.zeros((8, 6, 4), np.complex64), plan=plan)


def test_wrong_pencil_payload_fails_typed(devices):
    """A device payload that does not live where the plan expects fails
    THAT ticket with typed StaleRequestError — the batch's error never
    escapes the service."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    bad = pa.PencilArray.zeros(plan.output_pencil, (),
                               plan.dtype_spectral)
    t = svc.submit("t", bad, plan=plan, direction="forward")
    svc.drain()
    assert isinstance(t.error(), StaleRequestError)


def test_bad_payload_in_batch_fails_only_its_ticket(devices):
    """Blame-one-request payload problems stay one request's problem
    even INSIDE a coalesced batch: a stale device payload fails typed
    while the other tenant's request in the SAME batch completes."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(6)
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    stale = pa.PencilArray.zeros(plan.output_pencil, (),
                                 plan.dtype_spectral)
    good = _host(rng, (8, 6, 4))
    t_bad = svc.submit("alice", stale, plan=plan, direction="forward")
    t_good = svc.submit("bob", good, plan=plan, direction="forward")
    svc.drain()
    assert isinstance(t_bad.error(), StaleRequestError)
    ref = plan.compile(()).forward(
        pa.PencilArray.from_global(plan.input_pencil, good))
    assert np.array_equal(_np(t_good.result(5)), _np(ref)), \
        "a batch-mate's stale payload poisoned another tenant's ticket"
    assert svc.stats()["completed"] == {"ok": 1,
                                        "StaleRequestError": 1}


def test_malformed_host_shape_rejected_at_submit(devices):
    """A wrong-shape host payload is a typed error ON ITS SUBMITTER at
    submit time — it never enters the queue, so it can never break a
    coalesced stack under other tenants' requests."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    with pytest.raises(ServeError, match="shape"):
        svc.submit("t", np.zeros((9, 6, 4), np.complex64), plan=plan)
    assert svc.queue.depth() == 0


def test_complex_payload_to_r2c_plan_rejected_at_submit(devices):
    """A complex host payload against an r2c plan's real input is a
    typed error at submit — the coalesced ``np.asarray(dtype=float32)``
    cast would otherwise silently discard the imaginary part and return
    a numerically wrong transform marked ok."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4), real=True)
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    with pytest.raises(ServeError, match="imaginary"):
        svc.submit("t", np.zeros((8, 6, 4), np.complex64), plan=plan)
    assert svc.queue.depth() == 0


# ---------------------------------------------------------------------------
# tenant isolation: the ISSUE 10 acceptance drill
# ---------------------------------------------------------------------------


def _pa_obs_check(obs_dir):
    """Run the REAL post-mortem CLI over the drill's journal (the same
    path an operator types) and return the merged events."""
    from pencilarrays_tpu.obs.__main__ import main
    from pencilarrays_tpu.obs.timeline import merge_journals

    assert main(["lint", obs_dir]) == 0, "pa-obs lint failed"
    assert main(["timeline", obs_dir]) == 0, "pa-obs timeline failed"
    return merge_journals(obs_dir).events


@pytest.mark.chaos
def test_tenant_isolation_sdc_drill(devices, tmp_path):
    """``hop.exchange:corrupt`` poisoning one tenant's hop: that
    tenant's request raises typed ``IntegrityError`` while the other
    tenant's concurrently queued requests complete bit-identically to
    an unfaulted run — lifecycle journaled, ``pa-obs timeline``
    rendered."""
    obs_dir = str(tmp_path / "obs")
    obs.enable(obs_dir)
    guard.enable(str(tmp_path / "bundles"))
    topo = _topo2(devices)
    plan_a = PencilFFTPlan(topo, (6, 4, 4))     # cheap: dispatches first
    plan_b = PencilFFTPlan(topo, (12, 8, 6))
    rng = np.random.default_rng(11)
    ua = _host(rng, (6, 4, 4))
    ubs = [_host(rng, (12, 8, 6)) for _ in range(2)]
    svc = PlanService(max_batch=4, max_wait_s=0.0,
                      retry=RetryPolicy(max_attempts=1))
    # alice's batch dispatches first (cheapest); its FIRST exchange is
    # the poisoned hit — bob's batch is queued behind it throughout
    with faults.active("hop.exchange:corrupt*1@1"):
        ta = svc.submit("alice", ua, plan=plan_a)
        tbs = [svc.submit("bob", u, plan=plan_b) for u in ubs]
        svc.drain()
    err = ta.error()
    assert isinstance(err, IntegrityError), err
    with pytest.raises(IntegrityError):
        ta.result(1)
    # bob: bit-identical to the unfaulted run (guard armed, no faults:
    # the same eager isolation path the service dispatched through)
    for u, t in zip(ubs, tbs):
        ref = plan_b.forward(pa.PencilArray.from_global(
            plan_b.input_pencil, u))
        assert np.array_equal(_np(t.result(5)), _np(ref)), \
            "another tenant's request was poisoned"
    st = svc.stats()
    assert st["completed"] == {"ok": 2, "IntegrityError": 1}
    obs.disable()
    guard.disable()
    # the full lifecycle, through the real pa-obs CLI
    events = _pa_obs_check(obs_dir)
    reqs = [e for e in events if e["ev"] == "serve.request"]
    assert {e["tenant"] for e in reqs} == {"alice", "bob"}
    assert len([e for e in events if e["ev"] == "serve.coalesce"]) == 2
    assert len([e for e in events if e["ev"] == "serve.dispatch"]) == 2
    comp = {e["req"]: e for e in events if e["ev"] == "serve.complete"}
    assert comp[ta.id]["outcome"] == "IntegrityError"
    assert comp[ta.id]["tenant"] == "alice"
    assert all(comp[t.id]["outcome"] == "ok" for t in tbs)
    # the SDC detection + the recover ladder are attributed to alice's
    # dispatch (guarded_step meta= threading)
    sdc = [e for e in events if e["ev"] == "guard.sdc"]
    assert sdc, "no SDC detection journaled"
    rec = [e for e in events if e["ev"] == "guard.recover"]
    assert any(e.get("tenants") == ["alice"] for e in rec), rec
    # ...and the timeline text names the failure loudly
    from pencilarrays_tpu.obs.timeline import merge_journals, render

    txt = render(merge_journals(obs_dir))
    assert f"serve alice#{ta.id}:IntegrityError" in txt
    assert "serve.dispatch" in txt and "serve.coalesce" in txt


@pytest.mark.chaos
def test_isolation_same_tenant_later_traffic_unpoisoned(devices, tmp_path):
    """The poison is scoped to the BATCH, not the tenant or the
    service: the same tenant's next request (after the faulted batch)
    completes cleanly."""
    guard.enable(str(tmp_path / "bundles"))
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(2)
    svc = PlanService(max_batch=4, max_wait_s=0.0,
                      retry=RetryPolicy(max_attempts=1))
    u1, u2 = _host(rng, (8, 6, 4)), _host(rng, (8, 6, 4))
    with faults.active("hop.exchange:corrupt*1@1"):
        t1 = svc.submit("alice", u1, plan=plan)
        svc.drain()
        t2 = svc.submit("alice", u2, plan=plan)
        svc.drain()
    assert isinstance(t1.error(), IntegrityError)
    ref = plan.forward(pa.PencilArray.from_global(plan.input_pencil, u2))
    assert np.array_equal(_np(t2.result(5)), _np(ref))
    guard.disable()


@pytest.mark.chaos
def test_guarded_retry_recovers_transient_sdc(devices, tmp_path):
    """With retries allowed (the default ladder), a one-shot corrupt is
    TRANSIENT: guarded_step reruns the batch and the tickets resolve
    ok — serving inherits the guard's detect-and-recover semantics."""
    guard.enable(str(tmp_path / "bundles"))
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(4)
    u = _host(rng, (8, 6, 4))
    svc = PlanService(max_batch=4, max_wait_s=0.0,
                      retry=RetryPolicy(max_attempts=2, base_delay=0.01))
    with faults.active("hop.exchange:corrupt*1@1"):
        t = svc.submit("alice", u, plan=plan)
        svc.drain()
    ref = plan.forward(pa.PencilArray.from_global(plan.input_pencil, u))
    assert np.array_equal(_np(t.result(5)), _np(ref))
    guard.disable()


def test_guarded_step_meta_survives_reserved_key_names(tmp_path):
    """A ``meta=`` key named like one of guard.recover's own record
    fields (``label``/``stage``) — or like ``record_event``'s own
    parameters (``ev``/``_fsync``) — must not crash the ladder
    mid-recovery with a duplicate-kwarg error, nor silently act as the
    fsync override — the record's explicit fields win."""
    from pencilarrays_tpu.guard.recover import guarded_step

    obs.enable(str(tmp_path / "obs"))
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] == 1:
            raise IntegrityError("injected", hop="t")
        return "ok"

    out = guarded_step(
        fn, retry=RetryPolicy(max_attempts=2, base_delay=0.0),
        label="meta-step",
        meta={"label": "sneaky", "stage": "sneaky", "ev": "sneaky",
              "_fsync": "sneaky", "tenant": "alice"})
    assert out == "ok"
    recs = [e for e in obs_events.read_journal(str(tmp_path / "obs"))
            if e["ev"] == "guard.recover"]
    assert recs and all(e["label"] == "meta-step" for e in recs)
    assert all(e.get("tenant") == "alice" for e in recs)
    assert all("_fsync" not in e for e in recs)
    obs.disable()


# ---------------------------------------------------------------------------
# elastic rebind: named plans survive reformation
# ---------------------------------------------------------------------------


def test_named_plan_rebuild_rebinds_queue(devices):
    """The elastic-registered factory rebuilds the plan; queued
    host-payload requests re-bind and drain on the NEW plan object
    (the in-process half of the 2-rank drill in test_multiprocess)."""
    from pencilarrays_tpu.cluster import elastic

    topo = _topo2(devices)
    rng = np.random.default_rng(9)
    svc = PlanService(max_batch=4, max_wait_s=60.0)

    def factory(ctx=None):
        return PencilFFTPlan(_topo2(devices), (8, 6, 4), real=True)

    try:
        p0 = svc.register_plan("served", factory)
        assert svc.plan("served") is p0
        us = [_host(rng, (8, 6, 4), real=True) for _ in range(3)]
        ts = [svc.submit("t", u, name="served") for u in us[:2]]
        # a plan= submission that dedupes onto the same fingerprint
        # must re-bind too — it shares the coalesce key with the named
        # ones, and one dead-mesh straggler would poison the batch
        ts.append(svc.submit("t2", us[2], plan=p0))
        # simulate the reformation's registry pass: the serve factory
        # was registered as serve:<name> and re-invoking it must swap
        # the service's binding and re-bind the queued requests
        rebuilt = elastic._registry["serve:served"](None)
        assert svc.plan("served") is rebuilt and rebuilt is not p0
        assert rebuilt.plan_key() == p0.plan_key()
        assert all(e.plan is rebuilt
                   for e in svc.queue.pending_entries()), \
            "a queued entry kept the pre-reform plan object"
        svc.drain()
        cp = rebuilt.compile(())
        for u, t in zip(us, ts):
            ref = cp.forward(pa.PencilArray.from_global(
                rebuilt.input_pencil, u))
            assert np.array_equal(_np(t.result(5)), _np(ref))
        # close() must unregister the elastic factory: a dead service
        # must not be rebuilt into (and kept alive) by a later reform
        svc.close()
        assert "serve:served" not in elastic._registry
    finally:
        elastic.unregister_plan("serve:served")


# ---------------------------------------------------------------------------
# bench smoke (slow-marked: the sweep the suite's --serve arm commits)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_bench_smoke(devices, tmp_path):
    from benchmarks.serve_bench import run_serve_suite

    res = run_serve_suite(devices[:2], shapes=((8, 6, 4), (12, 8, 6)),
                          n_requests=8, max_batch=4, repeats=2)
    assert res["coalesced"]["requests_per_s"] > 0
    assert res["serialized"]["requests_per_s"] > 0
    assert res["speedup"] == pytest.approx(
        res["coalesced"]["requests_per_s"]
        / res["serialized"]["requests_per_s"])
    for arm in ("coalesced", "serialized"):
        for tstats in res[arm]["tenants"].values():
            assert tstats["p50_ms"] > 0 and tstats["p99_ms"] >= \
                tstats["p50_ms"]
    hlo = res["hlo_pin"]
    assert hlo["counts_equal_unbatched"], hlo
    assert hlo["predicted_equals_hlo"], hlo
    assert res["coalesced"]["dispatches"] < res["serialized"]["dispatches"]
