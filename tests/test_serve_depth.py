"""Depth stress for the admission-queue take path (PR 16).

The ROADMAP flagged that ``AdmissionQueue.take_ready`` and the
``LoadTracker`` projections had never been exercised past a handful of
queued entries.  At 10^4 the v1 take path went superlinear: every tick
rescanned EVERY pending group — O(groups) per call even when nothing
was due.  The fix indexes the take path (a full-group set, a lazy
coalesce-deadline heap, a lazy SLO-deadline heap) so a tick touches
only groups that can yield work.

The scaling pin is COUNTER-based, not wall-clock-based:
``AdmissionQueue.scan_stats()["groups_scanned"]`` must track due work,
not queue breadth — deterministic on any CI machine.  Batch formation
and dispatch ordering are pinned unchanged by tests/test_serve.py; this
file only pins what the take path *scans*.

PR 18 extends the same discipline to the LOAD-EXPORT path (the fleet
worker polls ``load_projection`` every 50 ms): ``depth()`` reads the
maintained depth index (``depth_entries_scanned`` stays 0 at any
depth, exact across every departure path) and the ``LoadTracker``
arrival window keeps a running cost sum (``arrivals_scanned`` stays 0
no matter how often the projection is read).
"""

from __future__ import annotations

import time

import pytest

from pencilarrays_tpu.serve.queue import (
    AdmissionQueue,
    TenantQuota,
    Ticket,
    _Entry,
)
from pencilarrays_tpu.serve.slo import LoadTracker

BIG = TenantQuota(max_requests=1 << 20, max_bytes=1 << 50)


def _entry(key: str, base: float, *, tenant: str = "t",
           deadline: float = None) -> _Entry:
    t = Ticket(tenant, "fft", key)
    t.t_submit = base
    return _Entry(ticket=t, plan=None, direction="forward",
                  payload=None, nbytes=1, plan_name=None,
                  deadline=deadline)


def _fill(q: AdmissionQueue, n_groups: int, per_group: int,
          base: float, prefix: str = "k") -> None:
    for g in range(n_groups):
        for _ in range(per_group):
            q.offer(_entry(f"{prefix}{g}", base))


def test_idle_ticks_scan_nothing_at_depth():
    # 10^4 queued entries, none due, none full: a hundred ticks must
    # not scan a single group (v1 scanned 2000 * 100)
    base = time.monotonic()
    q = AdmissionQueue(max_batch=8, max_wait_s=10.0,
                       default_quota=BIG)
    _fill(q, n_groups=2000, per_group=5, base=base)
    assert q.depth() == 10_000
    for _ in range(100):
        assert q.take_ready(now=base + 0.5) == []
    s = q.scan_stats()
    assert s["take_calls"] == 100
    assert s["groups_scanned"] == 0


def test_due_tick_scans_exactly_the_due_groups():
    base = time.monotonic()
    q = AdmissionQueue(max_batch=8, max_wait_s=1.0,
                       default_quota=BIG)
    _fill(q, n_groups=50, per_group=5, base=base)           # due at +1
    _fill(q, n_groups=30, per_group=5, base=base + 100.0,
          prefix="late")                                    # much later
    batches = q.take_ready(now=base + 2.0)
    # only the 50 due groups were touched; 30 not-due groups unscanned
    assert q.scan_stats()["groups_scanned"] == 50
    assert len(batches) == 50
    assert all(b.reason == "deadline" for b in batches)
    assert q.depth() == 150
    # the next idle tick scans nothing again
    assert q.take_ready(now=base + 2.5) == []
    assert q.scan_stats()["groups_scanned"] == 50


def test_full_group_surfaces_without_scanning_neighbors():
    base = time.monotonic()
    q = AdmissionQueue(max_batch=8, max_wait_s=10.0,
                       default_quota=BIG)
    _fill(q, n_groups=999, per_group=5, base=base)
    full = [q.offer(_entry("whale", base)) for _ in range(8)]
    assert full[-1] is True         # offer's fast-path signal
    batches = q.take_ready(now=base + 0.01)
    assert [b.key for b in batches] == ["whale"]
    assert batches[0].reason == "full"
    assert q.scan_stats()["groups_scanned"] == 1


def test_slo_expiry_wakes_only_the_affected_group():
    base = time.monotonic()
    q = AdmissionQueue(max_batch=8, max_wait_s=50.0,
                       default_quota=BIG)
    _fill(q, n_groups=500, per_group=2, base=base)
    q.offer(_entry("doomed", base, deadline=base + 0.1))
    q.take_ready(now=base + 0.5)
    assert q.scan_stats()["groups_scanned"] == 1
    dead = q.pop_expired()
    assert [e.ticket.key for e in dead] == ["doomed"]


def test_next_ready_in_is_heap_backed_and_correct():
    base = time.monotonic()
    q = AdmissionQueue(max_batch=8, max_wait_s=2.0,
                       default_quota=BIG)
    assert q.next_ready_in(now=base) is None
    _fill(q, n_groups=1000, per_group=10, base=base + 5.0)
    q.offer(_entry("old", base))    # the oldest head: due at +2
    got = q.next_ready_in(now=base + 1.0)
    assert got == pytest.approx(1.0, abs=1e-6)
    # an SLO deadline tighter than every coalesce deadline wins
    q.offer(_entry("slo", base + 5.0, deadline=base + 1.2))
    got = q.next_ready_in(now=base + 1.0)
    assert got == pytest.approx(0.2, abs=1e-6)
    # taking the due group re-arms to the next coalesce deadline
    q.take_ready(now=base + 2.0)
    assert q.next_ready_in(now=base + 2.0) == pytest.approx(
        5.0, abs=1e-6)


def test_remainder_after_full_split_reenters_the_index():
    base = time.monotonic()
    q = AdmissionQueue(max_batch=4, max_wait_s=1.0,
                       default_quota=BIG)
    for _ in range(6):
        q.offer(_entry("k", base))
    batches = q.take_ready(now=base + 0.01)     # full split: 4 taken
    assert [b.reason for b in batches] == ["full"]
    assert q.depth() == 2
    # the 2-entry remainder must still coalesce out at its deadline
    batches = q.take_ready(now=base + 2.0)
    assert [len(b.entries) for b in batches] == [2]
    assert q.depth() == 0


def test_load_tracker_projections_hold_at_depth():
    # the LoadTracker half of the ROADMAP flag: feeding 10^4 entries
    # and reading every projection stays O(window), no error, sane
    # values (its internals are deques — this pins the integration)
    base = time.monotonic()
    q = AdmissionQueue(max_batch=8, max_wait_s=10.0,
                       default_quota=BIG)
    for i in range(10_000):
        e = _entry(f"k{i % 100}", base)
        e.cost_bytes = 1000
        q.offer(e)
    snap = q.load.snapshot()
    assert snap["queued_cost_bytes"] == 10_000 * 1000
    q.load.note_completed(50 * 1000, 50, 0.5)
    assert q.load.projected_wait_s() is not None
    assert q.load.drain_s() is not None


def test_scan_work_tracks_due_work_not_depth():
    # THE scaling assertion: double the idle depth, the scan work of a
    # tick burst must not grow at all (v1 grew linearly)
    def scans_at(n_groups: int) -> int:
        base = time.monotonic()
        q = AdmissionQueue(max_batch=8, max_wait_s=10.0,
                          default_quota=BIG)
        _fill(q, n_groups=n_groups, per_group=5, base=base)
        for _ in range(50):
            q.take_ready(now=base + 0.5)
        return q.scan_stats()["groups_scanned"]

    assert scans_at(200) == 0
    assert scans_at(2000) == 0


# ---------------------------------------------------------------------------
# PR 18: the load-export path (depth index + arrival window) is O(1)
# ---------------------------------------------------------------------------

def _brute_depth(q: AdmissionQueue, tenant: str = None) -> int:
    entries = q.pending_entries()
    if tenant is None:
        return len(entries)
    return sum(1 for e in entries if e.ticket.tenant == tenant)


def test_depth_polls_scan_nothing_at_depth():
    # depth() sits on the fleet worker's 50ms load-export path: 10^4
    # queued entries, a thousand polls (total AND per-tenant), not one
    # entry rescanned (the v1 body re-counted every entry per call)
    base = time.monotonic()
    q = AdmissionQueue(max_batch=8, max_wait_s=10.0, default_quota=BIG)
    for g in range(1000):
        for t in ("whale", "minnow"):
            for _ in range(5):
                q.offer(_entry(f"{t}{g}", base, tenant=t))
    for _ in range(1000):
        assert q.depth() == 10_000
        assert q.depth("whale") == 5_000
        assert q.depth("minnow") == 5_000
        assert q.depth("ghost") == 0
    assert q.scan_stats()["depth_entries_scanned"] == 0


def test_depth_index_exact_across_every_departure_path():
    """The index must decrement at ALL four departure sites — full
    split, deadline flush, expired shed, pressure eviction — or the
    fleet's published load drifts from reality."""
    base = time.monotonic()
    q = AdmissionQueue(max_batch=4, max_wait_s=1.0, default_quota=BIG)
    # full split: 4 of 6 leave, the remainder stays indexed
    for _ in range(6):
        q.offer(_entry("k", base, tenant="a"))
    q.take_ready(now=base + 0.01)
    assert q.depth() == _brute_depth(q) == 2
    assert q.depth("a") == _brute_depth(q, "a") == 2
    # deadline flush: the remainder coalesces out
    q.take_ready(now=base + 2.0)
    assert q.depth() == _brute_depth(q) == 0
    assert q.depth("a") == 0
    # expired shed at the take point
    q.offer(_entry("doomed", base, tenant="b", deadline=base + 0.1))
    q.take_ready(now=base + 0.5)
    assert [e.ticket.key for e in q.pop_expired()] == ["doomed"]
    assert q.depth() == _brute_depth(q) == 0
    assert q.depth("b") == 0
    # pressure eviction: only the sheddable tier departs
    q.offer(_entry("low", base, tenant="c"))
    protected = _entry("high", base, tenant="d")
    protected.shed_priority = 5
    q.offer(protected)
    evicted = q.evict_sheddable(protected_priority=1)
    assert [e.ticket.tenant for e in evicted] == ["c"]
    assert q.depth() == _brute_depth(q) == 1
    assert q.depth("c") == 0 and q.depth("d") == 1
    # none of the above ever rescanned the queue to answer depth()
    assert q.scan_stats()["depth_entries_scanned"] == 0


def test_load_tracker_arrival_window_is_o1_and_exact():
    # the other half of the export path: arrival_cost_per_s must read
    # the maintained running sum (never rescan the window), and the
    # sum must stay exact under the deque's own evictions at 10^5
    tr = LoadTracker(window=64)
    now, costs = 1000.0, []
    for i in range(100_000):
        c = (i * 37) % 1000 + 1
        tr.note_arrival(c, now=now + i * 0.001)
        costs.append(c)
    for _ in range(1000):
        got = tr.arrival_cost_per_s()
    t0 = now + (100_000 - 64) * 0.001
    t1 = now + 99_999 * 0.001
    assert got == pytest.approx(sum(costs[-64:]) / (t1 - t0))
    assert tr.scan_stats()["arrivals_scanned"] == 0
