"""Overload-resilient serving (ISSUE 15): SLO deadlines at all three
enforcement points, the hysteretic load-shedding gate, eviction
determinism, the serve.submit fault point, the autoscaler's windowed
controller, and the reform-ordering fix (engine reform only after the
restore rung commits).

Boundary contracts under test (the satellite checklist):

* a request whose projected wait EQUALS its deadline exactly is
  admitted (strict-inequality rejection, pinned);
* shed hysteresis: storm -> recover is exactly two transitions, the
  band between the water marks never flaps the gate;
* eviction is deterministic in the submission sequence;
* ``AdmissionError(reason="shed")`` vs ``"queue-depth"`` vs
  ``"hbm-limit"`` vs ``DeadlineError`` reasons are never conflated;
* a restore-stage reformation failure resumes the OLD engines with
  their held dispatch queue INTACT (the PR-12 flagged hazard).
"""

import threading
import time

import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.ops.fft import PencilFFTPlan
from pencilarrays_tpu.resilience import faults
from pencilarrays_tpu.resilience.errors import InjectedFault
from pencilarrays_tpu.serve import (
    SLO,
    AdmissionError,
    AutoscalePolicy,
    Autoscaler,
    DeadlineError,
    PlanService,
    PressurePolicy,
    TenantQuota,
)
from pencilarrays_tpu.serve.shed import PressureGate

pytestmark = pytest.mark.usefixtures("devices")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (obs.ENV_VAR, faults.ENV_VAR, "PENCILARRAYS_TPU_RETRIES"):
        monkeypatch.delenv(var, raising=False)
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    yield
    faults.clear()
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()


def _topo2(devices):
    return pa.Topology((2,), devices=devices[:2])


def _host(rng, shape):
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


def _np(x):
    return np.asarray(pa.gather(x))


# ---------------------------------------------------------------------------
# the SLO declaration + projection plumbing
# ---------------------------------------------------------------------------


def test_slo_validation():
    SLO()                                   # all-default is legal
    SLO(deadline_s=1.0, p99_budget_s=2.0, shed_priority=3)
    with pytest.raises(ValueError):
        SLO(deadline_s=0.0)
    with pytest.raises(ValueError):
        SLO(p99_budget_s=-1.0)
    with pytest.raises(TypeError):
        PlanService(slos={"t": "not-an-slo"})


def test_load_tracker_projection_arithmetic():
    from pencilarrays_tpu.serve.slo import LoadTracker

    lt = LoadTracker()
    assert lt.rate_bytes_per_s() is None    # blind: no verdicts
    assert lt.projected_wait_s() is None
    lt.note_arrival(1000)
    lt.note_arrival(1000)
    assert lt.snapshot()["queued_cost_bytes"] == 2000
    # one measured completion sets the rate: 500 bytes-equiv / s
    lt.note_taken(1000)
    lt.note_completed(1000, 1, 2.0)
    assert lt.rate_bytes_per_s() == pytest.approx(500.0)
    # 1000 still queued -> 2 s projected drain, exact
    assert lt.drain_s() == pytest.approx(2.0)
    assert lt.projected_wait_s(250) == pytest.approx(0.5)
    # removal (shed/evict) stops the cost weighing immediately
    lt.note_removed(1000)
    assert lt.drain_s() == pytest.approx(0.0)


def test_disabled_path_prices_nothing(devices):
    """A service with no SLOs and no pressure policy must not price
    requests at admission (the PR-10 behavior AND overhead): the load
    tracker sees zero-cost arrivals and projects nothing."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(0)
    svc = PlanService(max_batch=4, max_wait_s=60.0)
    assert not svc._slo_armed
    svc.submit("t", _host(rng, (8, 6, 4)), plan=plan)
    assert svc.queue.load.snapshot()["queued_cost_bytes"] == 0
    assert svc.queue.load.projected_wait_s() is None
    svc.drain()
    assert svc.queue.load.rate_bytes_per_s() is None
    assert svc.stats()["pressure"] is None


# ---------------------------------------------------------------------------
# enforcement point 1: admission projection
# ---------------------------------------------------------------------------


def test_deadline_projection_boundary_exact_equality_admits(devices):
    """THE boundary pin: projected wait == deadline admits; any
    projection strictly beyond it rejects typed
    ``DeadlineError(reason="projected")`` — never a silent late
    answer."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(1)
    svc = PlanService(max_batch=8, max_wait_s=60.0,
                      slos={"bulk": SLO(shed_priority=0)})
    for _ in range(3):
        svc.submit("bulk", _host(rng, (8, 6, 4)), plan=plan)
    # seed the service rate: X bytes-equivalent per 2 s
    cost = svc.queue.load.snapshot()["queued_cost_bytes"] // 3
    assert cost > 0
    svc.queue.load.note_completed(cost, 1, 2.0)
    projected = svc.queue.load.projected_wait_s()
    assert projected is not None and projected > 0
    # equality: ADMITTED (strict-inequality contract)
    svc.set_slo("edge", SLO(deadline_s=projected))
    t_ok = svc.submit("edge", _host(rng, (8, 6, 4)), plan=plan)
    assert t_ok.error() is None
    # now the projection grew (one more queued request); a deadline
    # strictly below it is rejected with the projection attached
    projected2 = svc.queue.load.projected_wait_s()
    svc.set_slo("tight", SLO(deadline_s=projected2 * 0.5))
    with pytest.raises(DeadlineError) as ei:
        svc.submit("tight", _host(rng, (8, 6, 4)), plan=plan)
    assert ei.value.reason == "projected"
    assert ei.value.tenant == "tight"
    assert ei.value.projected_s == pytest.approx(projected2)
    assert ei.value.deadline_s == pytest.approx(projected2 * 0.5)
    # the rejection never entered the queue
    assert svc.queue.depth("tight") == 0
    svc.drain()


def test_blind_tracker_admits_everything(devices):
    """No completion history -> no projection -> deadlines cannot
    reject at admission (a never-measured service has no basis)."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(2)
    svc = PlanService(max_batch=4, max_wait_s=60.0,
                      slos={"dl": SLO(deadline_s=1e-9)})
    # far too tight to ever hold — but unprojectable, so admitted
    t = svc.submit("dl", _host(rng, (8, 6, 4)), plan=plan)
    assert t.error() is None
    svc.drain()


# ---------------------------------------------------------------------------
# enforcement point 2: take-side expiry shed
# ---------------------------------------------------------------------------


def test_expired_entry_shed_at_take_typed(devices, tmp_path):
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(3)
    obs.enable(str(tmp_path / "obs"))
    svc = PlanService(max_batch=4, max_wait_s=60.0,
                      slos={"dl": SLO(deadline_s=0.03)})
    t = svc.submit("dl", _host(rng, (8, 6, 4)), plan=plan)
    time.sleep(0.08)            # the deadline lapses in the queue
    assert svc.drain() == 0     # nothing dispatched
    with pytest.raises(DeadlineError) as ei:
        t.result(1)
    assert ei.value.reason == "expired"
    assert ei.value.tenant == "dl"
    assert svc.stats()["completed"] == {"DeadlineError": 1}
    # quota released: the tenant can submit again
    svc.submit("dl", _host(rng, (8, 6, 4)), plan=plan)
    svc.drain()
    obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    comp = [e for e in events if e["ev"] == "serve.complete"
            and e["outcome"] == "DeadlineError"]
    assert len(comp) == 1 and comp[0]["req"] == t.id
    counters = obs_metrics.snapshot()["counters"]
    assert counters["serve.shed{reason=expired,tenant=dl}"] == 1


def test_expiry_feeds_pump_deadline(devices):
    """The deadline-aware pump tick: ``next_ready_in`` is bounded by
    the earliest queued SLO deadline, so a streaming service wakes to
    shed an expiring entry instead of waiting out the coalesce
    window."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(4)
    svc = PlanService(max_batch=8, max_wait_s=30.0,
                      slos={"dl": SLO(deadline_s=0.05)})
    svc.submit("dl", _host(rng, (8, 6, 4)), plan=plan)
    wait = svc.queue.next_ready_in()
    assert wait is not None and wait <= 0.05 + 1e-3, wait
    svc.drain()


def test_streaming_pump_sheds_at_slo_deadline(devices):
    """Live streaming regression (found by end-to-end verify): the
    pump's INITIAL arm must honor a queued SLO deadline far inside the
    coalesce window — the expired entry is shed typed at ~its deadline,
    not discovered a full ``max_wait_s`` later."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(15)
    svc = PlanService(max_batch=8, max_wait_s=5.0,
                      slos={"dl": SLO(deadline_s=0.1, shed_priority=1)})
    svc.start()
    t0 = time.monotonic()
    t = svc.submit("dl", _host(rng, (8, 6, 4)), plan=plan)
    with pytest.raises(DeadlineError) as ei:
        t.result(3)
    assert ei.value.reason == "expired"
    assert time.monotonic() - t0 < 2.0, \
        "the pump waited out the coalesce window instead of the deadline"
    svc.close()


# ---------------------------------------------------------------------------
# enforcement point 3: late completion journaled
# ---------------------------------------------------------------------------


def test_late_completion_journals_slo_violation(devices, tmp_path):
    """A request dispatched in time but finished late RETURNS its
    result and journals fsync-critical ``serve.slo_violation`` with
    per-tenant counters — enforced, visible, never silent."""
    topo = _topo2(devices)
    # a fresh shape: the first dispatch pays XLA compile, far beyond
    # the deadline — deterministic lateness without sleeping
    plan = PencilFFTPlan(topo, (10, 8, 6))
    rng = np.random.default_rng(5)
    obs.enable(str(tmp_path / "obs"))
    svc = PlanService(max_batch=4, max_wait_s=60.0,
                      slos={"dl": SLO(deadline_s=0.02,
                                      p99_budget_s=0.05)})
    u = _host(rng, (10, 8, 6))
    t = svc.submit("dl", u, plan=plan)
    svc.drain()                 # takes immediately: not expired-shed
    ref = plan.compile(()).forward(pa.PencilArray.from_global(
        plan.input_pencil, u))
    assert np.array_equal(_np(t.result(5)), _np(ref)), \
        "a late completion must still return the (correct) answer"
    assert svc.stats()["slo_violations"] == 1
    obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    viol = [e for e in events if e["ev"] == "serve.slo_violation"]
    assert len(viol) == 1
    assert viol[0]["tenant"] == "dl" and viol[0]["req"] == t.id
    assert viol[0]["deadline_s"] == pytest.approx(0.02)
    assert viol[0]["late_s"] > 0
    counters = obs_metrics.snapshot()["counters"]
    assert counters["serve.slo_violations{tenant=dl}"] == 1
    # schema-clean through the real CLI path
    from pencilarrays_tpu.obs.__main__ import main

    assert main(["lint", str(tmp_path / "obs")]) == 0
    assert main(["timeline", str(tmp_path / "obs")]) == 0


# ---------------------------------------------------------------------------
# the pressure gate: hysteresis, shed, evict
# ---------------------------------------------------------------------------


def test_pressure_gate_hysteresis_no_flap(tmp_path):
    """Storm -> recover is exactly TWO transitions; the band between
    the water marks holds the current state in both directions."""
    obs.enable(str(tmp_path / "obs"))
    gate = PressureGate(PressurePolicy(high_water_s=0.1, low_water_s=0.05))
    assert gate.state == "ok"
    assert gate.update(0.07) == "ok"        # band: ok holds
    assert gate.update(0.12) == "shed"      # storm crosses high water
    assert gate.update(0.07) == "shed"      # band: shed holds (no flap)
    assert gate.update(0.09) == "shed"
    assert gate.update(0.04) == "ok"        # recovery below LOW water
    assert gate.update(0.07) == "ok"        # band again: still ok
    assert gate.transitions == 2, \
        "storm->recover must be exactly two transitions, no flapping"
    assert gate.update(None) == "ok"        # blind projection: no-op
    obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    trans = [(e["prev"], e["state"]) for e in events
             if e["ev"] == "serve.pressure"]
    assert trans == [("ok", "shed"), ("shed", "ok")]


def test_pressure_gate_recovers_at_zero_low_water():
    """``low_water_s=0`` is legal — a fully-drained queue projects
    EXACTLY 0.0 and must reopen the gate (at-or-below semantics), not
    wedge it shut forever."""
    gate = PressureGate(PressurePolicy(high_water_s=1.0, low_water_s=0.0))
    assert gate.update(2.0) == "evict"
    assert gate.update(0.0) == "ok"


def test_pressure_gate_evict_escalation():
    gate = PressureGate(PressurePolicy(high_water_s=0.1, low_water_s=0.05,
                                       evict_water_s=0.3))
    assert gate.update(0.15) == "shed"
    assert not gate.evicting()
    assert gate.update(0.35) == "evict"     # the second rung
    assert gate.evicting()
    assert gate.update(0.2) == "shed"       # de-escalates below evict
    assert gate.update(0.01) == "ok"
    with pytest.raises(ValueError):
        PressurePolicy(high_water_s=0.1, low_water_s=0.2)
    with pytest.raises(ValueError):
        PressurePolicy(high_water_s=0.1, evict_water_s=0.05)


def _storm_service(devices, rng, *, evict_water_s=None):
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    svc = PlanService(
        max_batch=8, max_wait_s=60.0,
        slos={"prot": SLO(shed_priority=10),
              "bulk": SLO(shed_priority=0)},
        pressure=PressurePolicy(high_water_s=0.5, low_water_s=0.1,
                                evict_water_s=evict_water_s))
    return svc, plan


def test_shed_at_submit_protects_high_priority(devices, tmp_path):
    """Over high water the gate sheds the sheddable tier typed at
    submit; the protected tier keeps flowing; recovery re-opens the
    gate."""
    obs.enable(str(tmp_path / "obs"))
    rng = np.random.default_rng(6)
    svc, plan = _storm_service(devices, rng)
    u = _host(rng, (8, 6, 4))
    for _ in range(2):
        svc.submit("prot", u, plan=plan)
    cost = svc.queue.load.snapshot()["queued_cost_bytes"] // 2
    svc.queue.load.note_completed(cost, 1, 10.0)    # very slow service
    assert svc.queue.load.drain_s() > 0.5
    with pytest.raises(AdmissionError) as ei:
        svc.submit("bulk", u, plan=plan)
    assert ei.value.reason == "shed" and ei.value.tenant == "bulk"
    # the protected tenant is NEVER shed
    t = svc.submit("prot", u, plan=plan)
    assert t.error() is None
    # an SLO-less tenant defaults to priority 0: sheddable
    with pytest.raises(AdmissionError) as ei2:
        svc.submit("anon", u, plan=plan)
    assert ei2.value.reason == "shed"
    # recovery: drain the queue, feed a fast completion, gate reopens
    svc.queue.load.note_completed(100 * cost, 1, 0.001)
    svc.drain()
    assert svc.queue.load.drain_s() < 0.1
    t2 = svc.submit("bulk", u, plan=plan)
    assert t2.error() is None
    svc.drain()
    obs.disable()
    counters = obs_metrics.snapshot()["counters"]
    assert counters["serve.rejected{reason=shed,tenant=bulk}"] == 1
    assert counters["serve.rejected{reason=shed,tenant=anon}"] == 1


def test_evict_rung_deterministic_in_submission_sequence(devices,
                                                         tmp_path):
    """The second rung: already-queued sheddable entries are evicted in
    admission-sequence order — exactly the sheddable ones, exactly
    once, protected entries untouched."""
    obs.enable(str(tmp_path / "obs"))
    rng = np.random.default_rng(7)
    svc, plan = _storm_service(devices, rng, evict_water_s=1.0)
    u = _host(rng, (8, 6, 4))
    tickets = {}
    for name in ("bulk", "prot", "bulk", "prot", "bulk"):
        t = svc.submit(name, u, plan=plan)
        tickets.setdefault(name, []).append(t)
    cost = svc.queue.load.snapshot()["queued_cost_bytes"] // 5
    svc.queue.load.note_completed(cost, 1, 10.0)    # drain >> evict_at
    assert svc.queue.load.drain_s() > 1.0
    # the next maintenance pass (any dispatch path) runs the rung
    svc._slo_maintenance()
    evicted = [t for t in tickets["bulk"] if t.done()]
    assert len(evicted) == 3, "every sheddable entry evicts, exactly once"
    for t in tickets["bulk"]:
        assert isinstance(t.error(), AdmissionError)
        assert t.error().reason == "shed"
    # eviction order == admission order (ticket ids ascend with seq)
    events = obs_events.read_journal(str(tmp_path / "obs"))
    shed_reqs = [e["req"] for e in events if e["ev"] == "serve.complete"
                 and e["outcome"] == "AdmissionError"]
    assert shed_reqs == sorted(t.id for t in tickets["bulk"])
    for t in tickets["prot"]:
        assert not t.done(), "a protected entry was evicted"
    svc.drain()
    for t in tickets["prot"]:
        assert t.error() is None
    obs.disable()


def test_admission_reasons_never_conflated(devices):
    """``shed`` vs ``queue-depth`` vs ``inflight-bytes`` vs
    ``hbm-limit`` vs the two DeadlineError reasons: distinct types /
    reason strings, each from its own enforcement point."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(8)
    u = _host(rng, (8, 6, 4))
    # quota reasons (PR-10 semantics untouched by the SLO layer)
    svc = PlanService(max_batch=8, max_wait_s=60.0,
                      quotas={"small": TenantQuota(max_requests=1),
                              "thin": TenantQuota(max_bytes=10)},
                      slos={"prot": SLO(shed_priority=1)},
                      pressure=PressurePolicy(high_water_s=0.1,
                                              low_water_s=0.05))
    svc.submit("small", u, plan=plan)
    with pytest.raises(AdmissionError) as e1:
        svc.submit("small", u, plan=plan)
    with pytest.raises(AdmissionError) as e2:
        svc.submit("thin", u, plan=plan)
    # force the gate shut: shed reason is distinct from both
    cost = max(1, svc.queue.load.snapshot()["queued_cost_bytes"])
    svc.queue.load.note_completed(cost, 1, 100.0)
    with pytest.raises(AdmissionError) as e3:
        svc.submit("bulk", u, plan=plan)
    reasons = {e1.value.reason, e2.value.reason, e3.value.reason}
    assert reasons == {"queue-depth", "inflight-bytes", "shed"}
    svc.drain()
    # hbm-limit rides its own service knob (typed at submit, reshard)
    topo4 = pa.Topology((2, 2), devices=devices[:4])
    src = pa.Pencil(topo4, (8, 6, 4), (1, 2))
    dst = pa.Pencil(topo4, (8, 6, 4), (0, 2))
    x = pa.PencilArray.from_global(src, _host(rng, (8, 6, 4)))
    svc2 = PlanService(hbm_limit=1)     # nothing routes under 1 byte
    with pytest.raises(AdmissionError) as e4:
        svc2.submit_reshard("whale", x, dst)
    assert e4.value.reason == "hbm-limit"
    # DeadlineError is a different TYPE with its own reasons
    assert not isinstance(e4.value, DeadlineError)
    assert {r for r in ("projected", "expired")} \
        .isdisjoint({e1.value.reason, e2.value.reason,
                     e3.value.reason, e4.value.reason})


# ---------------------------------------------------------------------------
# the serve.submit fault point
# ---------------------------------------------------------------------------


def test_serve_submit_fault_point(devices):
    """``serve.submit:error`` fails the submitter typed at the
    admission boundary — before any queue state changes — and the
    counter addressing (@nth) works like every other point."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(9)
    svc = PlanService(max_batch=4, max_wait_s=60.0)
    u = _host(rng, (8, 6, 4))
    with faults.active("serve.submit:error*1@2"):
        t1 = svc.submit("t", u, plan=plan)      # hit 1: clean
        with pytest.raises(InjectedFault):      # hit 2: injected, once
            svc.submit("t", u, plan=plan)
        t3 = svc.submit("t", u, plan=plan)      # hit 3: clean again
        assert t3.error() is None
    assert svc.queue.depth() == 2, \
        "an injected admission failure must not enter the queue"
    svc.drain()
    assert t1.error() is None


def test_serve_submit_fault_point_delay_mode(devices):
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(10)
    svc = PlanService(max_batch=4, max_wait_s=60.0)
    u = _host(rng, (8, 6, 4))
    with faults.active("serve.submit:delay@1"), \
            pytest.MonkeyPatch.context() as mp:
        mp.setenv(faults.DELAY_S_VAR, "0.15")
        t0 = time.monotonic()
        svc.submit("t", u, plan=plan)
        assert time.monotonic() - t0 >= 0.15    # dragged, then admitted
    assert svc.queue.depth() == 1
    svc.drain()


# ---------------------------------------------------------------------------
# the autoscaler controller (unit: no cluster; the round trip rides
# the FileKV drill in test_multiprocess.py)
# ---------------------------------------------------------------------------


def _loaded_service(devices, rng, drain_s):
    """A service whose projection reads ``drain_s`` of queued work."""
    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    svc = PlanService(max_batch=8, max_wait_s=60.0,
                      slos={"t": SLO(shed_priority=0)})
    svc.submit("t", _host(rng, (8, 6, 4)), plan=plan)
    cost = svc.queue.load.snapshot()["queued_cost_bytes"]
    svc.queue.load.note_completed(cost, 1, drain_s)  # rate = cost/drain
    return svc


def test_autoscaler_requires_consecutive_windows(devices, tmp_path):
    obs.enable(str(tmp_path / "obs"))
    rng = np.random.default_rng(11)
    svc = _loaded_service(devices, rng, drain_s=5.0)
    asc = Autoscaler(svc, policy=AutoscalePolicy(
        overload_drain_s=1.0, windows=3, cooldown_s=0.0))
    assert asc.tick().direction == "hold"
    assert asc.tick().direction == "hold"
    d = asc.tick()      # third consecutive overload window: decide
    assert d.direction == "up" and d.reason == "overload"
    assert not d.acted and d.detail == "no-coordinator"
    assert d.projection["drain_s"] == pytest.approx(5.0)
    # the streak was consumed: the very next tick holds again
    assert asc.tick().direction == "hold"
    obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    scale = [e for e in events if e["ev"] == "serve.scale"]
    assert len(scale) == 1
    assert scale[0]["direction"] == "up"
    assert scale[0]["reason"] == "overload"
    assert scale[0]["projection"]["drain_s"] == pytest.approx(5.0)
    assert scale[0]["acted"] is False
    svc.drain()


def test_autoscaler_interrupted_streak_never_decides(devices):
    rng = np.random.default_rng(12)
    svc = _loaded_service(devices, rng, drain_s=5.0)
    asc = Autoscaler(svc, policy=AutoscalePolicy(
        overload_drain_s=1.0, windows=2, cooldown_s=0.0))
    assert asc.tick().direction == "hold"   # overload window 1
    svc.drain()                             # load vanishes
    assert asc.tick().direction == "hold"   # idle window 1 (streak reset)
    assert asc.decisions == 0


def test_autoscaler_cooldown_rate_limits(devices):
    rng = np.random.default_rng(13)
    svc = _loaded_service(devices, rng, drain_s=5.0)
    asc = Autoscaler(svc, policy=AutoscalePolicy(
        overload_drain_s=1.0, windows=1, cooldown_s=3600.0))
    assert asc.tick().direction == "up"     # first decision fires
    d = asc.tick()                          # still overloaded...
    assert d.direction == "hold" and d.reason == "cooldown"
    assert asc.decisions == 1
    svc.drain()


def test_autoscaler_idle_scales_down(devices, tmp_path):
    obs.enable(str(tmp_path / "obs"))
    topo = _topo2(devices)
    svc = PlanService(max_batch=4, slos={"t": SLO(shed_priority=0)})
    del topo
    asc = Autoscaler(svc, policy=AutoscalePolicy(
        overload_drain_s=1.0, windows=2, cooldown_s=0.0))
    assert asc.tick().direction == "hold"
    d = asc.tick()
    assert d.direction == "down" and d.reason == "idle"
    assert not d.acted and d.detail == "no-coordinator"
    obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    assert [e["direction"] for e in events
            if e["ev"] == "serve.scale"] == ["down"]


def test_autoscaler_down_designates_highest_rank(tmp_path):
    """Every rank computes the same decision; only the highest-rank
    member flags itself for departure (announce_leave)."""
    from pencilarrays_tpu.cluster.consensus import Coordinator
    from pencilarrays_tpu.cluster.kv import FileKV

    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 2, lease_ttl=30.0, verdict_timeout=20)
    c1 = Coordinator(kv, 1, 2, lease_ttl=30.0, verdict_timeout=20)
    try:
        svc = PlanService(max_batch=4, slos={"t": SLO(shed_priority=0)})
        pol = AutoscalePolicy(windows=1, cooldown_s=0.0, min_world=1)
        a0 = Autoscaler(svc, coordinator=c0, policy=pol)
        a1 = Autoscaler(svc, coordinator=c1, policy=pol)
        d0, d1 = a0.tick(), a1.tick()
        assert (d0.direction, d1.direction) == ("down", "down")
        assert not d0.acted and d0.detail == "not-leaver"
        assert d1.acted and d1.detail == "leaving-rank=1"
        assert c1.leaving and not c0.leaving
        # min_world floor refuses to shrink a 2-world below 2
        a2 = Autoscaler(svc, coordinator=c0, policy=AutoscalePolicy(
            windows=1, cooldown_s=0.0, min_world=2))
        d = a2.tick()
        assert d.direction == "down" and d.detail == "at-min-world"
    finally:
        c0.shutdown()
        c1.shutdown()


def test_prewarm_plans_compiles_and_reports(devices, tmp_path):
    from pencilarrays_tpu.serve.autoscale import prewarm_plans

    obs.enable(str(tmp_path / "obs"))
    topo = _topo2(devices)

    def factory(ctx=None):
        return PencilFFTPlan(topo, (8, 6, 4), real=True)

    rep = prewarm_plans({"warm": factory})
    assert rep["plans"] == 1 and rep["warm_s"] > 0
    assert "warm" in rep["per_plan_s"]
    obs.disable()
    events = obs_events.read_journal(str(tmp_path / "obs"))
    pre = [e for e in events if e["ev"] == "serve.scale"
           and e["reason"] == "prewarm"]
    assert len(pre) == 1 and pre[0]["projection"]["plans"] == 1


# ---------------------------------------------------------------------------
# the reform-ordering fix (PR-12 flagged hazard, satellite 1)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_restore_failure_resumes_engines_with_held_queue(devices,
                                                         tmp_path):
    """A restore-stage reformation failure must resume the OLD mesh
    with every held engine dispatch INTACT: before the reorder,
    ``reform_all`` ran in the replan stage and a restore failure left
    the held dispatches already failed typed — contradicting the
    quiesce site's hold-until-commit comment.  Now the held dispatch
    survives the failed reformation and EXECUTES on resume."""
    from pencilarrays_tpu import cluster
    from pencilarrays_tpu.cluster import elastic
    from pencilarrays_tpu.cluster.consensus import Coordinator
    from pencilarrays_tpu.cluster.errors import ReformError
    from pencilarrays_tpu.cluster.kv import FileKV
    from pencilarrays_tpu.engine import get_engine
    from pencilarrays_tpu.resilience import CheckpointManager

    engine = get_engine()
    gen0 = engine.generation
    assert engine.quiesce(5)
    held = engine.submit(lambda: "held-survives", label="held")
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 1, lease_ttl=5.0, verdict_timeout=20)
    # an EMPTY checkpoint manager: membership/mesh/replan succeed, the
    # restore rung fails (no valid step anywhere)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    try:
        with pytest.raises(ReformError) as ei:
            elastic.reform(c0, reason="drill", install=False,
                           ckpt_mgr=mgr, restore=lambda c: None)
        assert ei.value.stage == "restore"
        # the engines were NEVER reformed (the fix: reform_all runs
        # only after the restore rung commits)...
        assert engine.generation == gen0
        # ...and the failed reformation resumed them: the held dispatch
        # executes with its RESULT — not EngineReformedError
        assert held.result(10) == "held-survives"
    finally:
        c0.shutdown()
        cluster._reset_for_tests()


@pytest.mark.chaos
def test_successful_reform_still_drops_held_dispatches(devices,
                                                       tmp_path):
    """The flip side: when the reformation COMMITS, held dispatches
    fail typed (their programs target the dead mesh) — the reorder
    must not silently start dispatching stale programs."""
    from pencilarrays_tpu import cluster
    from pencilarrays_tpu.cluster import elastic
    from pencilarrays_tpu.cluster.consensus import Coordinator
    from pencilarrays_tpu.cluster.kv import FileKV
    from pencilarrays_tpu.engine import get_engine
    from pencilarrays_tpu.engine.errors import EngineReformedError
    from pencilarrays_tpu.resilience import CheckpointManager

    engine = get_engine()
    gen0 = engine.generation
    assert engine.quiesce(5)
    held = engine.submit(lambda: "never", label="held")
    kv = FileKV(str(tmp_path / "kv"))
    c0 = Coordinator(kv, 0, 1, lease_ttl=5.0, verdict_timeout=20)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    state = {"u": pa.PencilArray.from_global(
        pa.Pencil(pa.Topology((1,), devices=devices[:1]), (4, 4), (0,)),
        np.ones((4, 4), np.float32))}
    mgr.save(1, state)
    try:
        r = elastic.reform(c0, reason="drill", install=False,
                           ckpt_mgr=mgr, restore=lambda c: None)
        assert r.restored_step == 1
        assert engine.generation == gen0 + 1
        with pytest.raises(EngineReformedError):
            held.result(10)
        r.coordinator.shutdown()
    finally:
        c0.shutdown()
        cluster._reset_for_tests()


# ---------------------------------------------------------------------------
# engine-reformation resubmission: no ticket stranded
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# bench smoke (slow-marked: the sweep the suite's --autoscale arm commits)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_autoscale_bench_smoke(devices, tmp_path):
    from benchmarks.autoscale_bench import run_autoscale_suite

    res = run_autoscale_suite(devices[:2], workdir=str(tmp_path),
                              waves=2, warm_join=False)
    storm = res["storm"]["storm"]
    assert storm["shed_precision"] == 1.0
    assert storm["shed_recall"] == 1.0
    assert storm["protected"]["p99_ms"] > 0
    assert res["storm"]["unloaded"]["shed_typed_at_submit"] == 0
    assert res["disabled_path"]["serve_rerun"][
        "coalesced_at_least_serialized"]
    assert res["controller"]["tick_us"] < 1000


@pytest.mark.chaos
def test_reformed_engine_batch_resubmits_instead_of_stranding(devices):
    """A serve batch whose engine task was dropped typed by
    ``Engine.reform`` is parked and resubmitted onto the reformed
    engine — the ticket resolves with its RESULT, not
    EngineReformedError (the no-ticket-stranded contract)."""
    from pencilarrays_tpu.engine import get_engine

    topo = _topo2(devices)
    plan = PencilFFTPlan(topo, (8, 6, 4))
    rng = np.random.default_rng(14)
    engine = get_engine()
    svc = PlanService(max_batch=4, max_wait_s=0.0)
    u = _host(rng, (8, 6, 4))
    assert engine.quiesce(5)        # hold the dispatch queue
    t = svc.submit("t", u, plan=plan)
    stepper = threading.Thread(target=svc.step,
                               kwargs={"flush": True}, daemon=True)
    stepper.start()
    deadline = time.monotonic() + 10
    while engine.depth() == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert engine.depth() == 1, "batch never reached the held engine"
    engine.reform()                 # drops the queued task typed
    stepper.join(timeout=10)
    assert not stepper.is_alive()
    assert not t.done(), "the ticket must await resubmission, not fail"
    svc.step(flush=True)            # safe point: flushes the parked batch
    ref = plan.compile(()).forward(pa.PencilArray.from_global(
        plan.input_pencil, u))
    assert np.array_equal(_np(t.result(10)), _np(ref))
    assert svc.stats()["completed"] == {"ok": 1}
    svc.close()
