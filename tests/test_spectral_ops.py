"""Spectral differential operators vs analytic ground truth.

Fields are trigonometric, so gradients/divergence/curl/Laplacian have
closed forms; everything is checked through full plan round trips on the
8-device mesh (collectives included).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import PencilArray, PencilFFTPlan, Topology, gather
from pencilarrays_tpu.ops import (
    curl,
    divergence,
    gradient,
    laplacian,
    solve_poisson,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


N = (16, 12, 10)


def _grid(shape):
    axes = [np.arange(n) * (2 * np.pi / n) for n in shape]
    return np.meshgrid(*axes, indexing="ij")


def _plan(topo):
    return PencilFFTPlan(topo, N, real=True, dtype=jnp.float64)


def test_gradient_analytic(topo):
    plan = _plan(topo)
    X, Y, Z = _grid(N)
    f = np.sin(2 * X) * np.cos(Y) + np.sin(3 * Z)
    fh = plan.forward(PencilArray.from_global(plan.input_pencil, f))
    gh = gradient(plan, fh)
    assert gh.extra_dims == (3,)
    g = [gather(plan.backward(gh.component(d))) for d in range(3)]
    np.testing.assert_allclose(g[0], 2 * np.cos(2 * X) * np.cos(Y),
                               atol=1e-10)
    np.testing.assert_allclose(g[1], -np.sin(2 * X) * np.sin(Y),
                               atol=1e-10)
    np.testing.assert_allclose(g[2], 3 * np.cos(3 * Z), atol=1e-10)


def test_divergence_of_gradient_is_laplacian(topo):
    plan = _plan(topo)
    X, Y, Z = _grid(N)
    f = np.cos(X) * np.cos(2 * Y) * np.sin(Z)
    fh = plan.forward(PencilArray.from_global(plan.input_pencil, f))
    div_grad = gather(plan.backward(divergence(plan, gradient(plan, fh))))
    lap = gather(plan.backward(laplacian(plan, fh)))
    np.testing.assert_allclose(div_grad, lap, atol=1e-10)
    np.testing.assert_allclose(lap, -(1 + 4 + 1) * f, atol=1e-9)


def test_curl_analytic(topo):
    plan = _plan(topo)
    X, Y, Z = _grid(N)
    # u = (sin(y), 0, 0) -> curl u = (0, 0, -cos(y))
    u = np.stack([np.sin(Y), np.zeros(N), np.zeros(N)], axis=-1)
    uh = PencilArray.stack([
        plan.forward(PencilArray.from_global(plan.input_pencil,
                                             u[..., d]))
        for d in range(3)])
    w = curl(plan, uh)
    wz = gather(plan.backward(w.component(2)))
    np.testing.assert_allclose(wz, -np.cos(Y), atol=1e-10)
    w0 = gather(plan.backward(w.component(0)))
    np.testing.assert_allclose(w0, 0.0, atol=1e-10)


def test_curl_of_gradient_is_zero(topo):
    plan = _plan(topo)
    X, Y, Z = _grid(N)
    f = np.sin(X + 2 * Y) * np.cos(Z)
    fh = plan.forward(PencilArray.from_global(plan.input_pencil, f))
    w = curl(plan, gradient(plan, fh))
    for d in range(3):
        np.testing.assert_allclose(gather(plan.backward(w.component(d))),
                                   0.0, atol=1e-9)


def test_poisson_solve(topo):
    plan = _plan(topo)
    X, Y, Z = _grid(N)
    phi_true = np.sin(X) * np.cos(2 * Y) * np.sin(3 * Z)
    f = -(1 + 4 + 9) * phi_true  # lap(phi_true)
    fh = plan.forward(PencilArray.from_global(plan.input_pencil, f))
    phi = gather(plan.backward(solve_poisson(plan, fh)))
    np.testing.assert_allclose(phi, phi_true, atol=1e-10)


def test_box_lengths(topo):
    """Non-2*pi box: k scales by 2*pi/L."""
    plan = _plan(topo)
    L = (1.0, 2 * np.pi, 2 * np.pi)
    x = np.arange(N[0]) / N[0]  # box length 1 along x
    X = x[:, None, None] * np.ones(N)
    f = np.sin(2 * np.pi * 2 * X)  # mode 2 in a unit box
    fh = plan.forward(PencilArray.from_global(plan.input_pencil, f))
    gx = gather(plan.backward(
        gradient(plan, fh, lengths=L).component(0)))
    np.testing.assert_allclose(gx, 4 * np.pi * np.cos(4 * np.pi * X),
                               atol=1e-8)


def test_operand_validation(topo):
    plan = _plan(topo)
    wrong = PencilArray.zeros(plan.input_pencil, (), jnp.complex128)
    with pytest.raises(ValueError, match="output_pencil"):
        gradient(plan, wrong)
    fh = PencilArray.zeros(plan.output_pencil, (), jnp.complex128)
    with pytest.raises(ValueError, match="vector"):
        divergence(plan, fh)
    with pytest.raises(ValueError, match="lengths"):
        laplacian(plan, fh, lengths=(1.0,))


def test_laplacian_on_vector_field(topo):
    """Vector fields (extra dims) broadcast componentwise — the viscous
    term shape of the NS model."""
    plan = _plan(topo)
    X, Y, Z = _grid(N)
    comps = [np.sin(X), np.cos(2 * Y), np.sin(Z + X)]
    uh = PencilArray.stack([
        plan.forward(PencilArray.from_global(plan.input_pencil, c))
        for c in comps])
    lap = laplacian(plan, uh)
    assert lap.extra_dims == (3,)
    np.testing.assert_allclose(
        gather(plan.backward(lap.component(0))), -np.sin(X), atol=1e-9)
    np.testing.assert_allclose(
        gather(plan.backward(lap.component(1))), -4 * np.cos(2 * Y),
        atol=1e-9)
    # poisson on the vector field inverts it (zero modes excluded)
    back = solve_poisson(plan, lap)
    for d, c in enumerate(comps):
        np.testing.assert_allclose(
            gather(plan.backward(back.component(d))), c - c.mean(),
            atol=1e-9)


def test_gradient_with_batch_extra_dims(topo):
    """Batch extra dims broadcast; components stack into a NEW trailing
    dim (regression: unaligned wavenumbers silently differentiated the
    wrong axis when a batch extent matched a spectral extent)."""
    plan = _plan(topo)
    X, Y, Z = _grid(N)
    fields = [np.sin(X), np.cos(Y) * np.sin(Z)]
    fh = PencilArray.stack([
        plan.forward(PencilArray.from_global(plan.input_pencil, f))
        for f in fields])  # extra_dims (2,): a batch of scalars
    gh = gradient(plan, fh)
    assert gh.extra_dims == (2, 3)
    gx0 = gather(plan.backward(gh.component(0, 0)))
    np.testing.assert_allclose(gx0, np.cos(X), atol=1e-9)
    gy1 = gather(plan.backward(gh.component(1, 1)))
    np.testing.assert_allclose(gy1, -np.sin(Y) * np.sin(Z), atol=1e-9)
