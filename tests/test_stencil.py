"""Finite-difference stencil tests: shift semantics vs numpy ground
truth across decompositions/permutations/padded dims, the GSPMD halo
HLO budget (neighbor collective-permutes only, never an all-gather),
FD operators, differentiability, and decomposition independence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu.ops import (
    diff,
    fd_divergence,
    fd_gradient,
    fd_laplacian,
    shift,
)
from pencilarrays_tpu.utils.hlo import collective_stats


def _np_shift_zero(g, axis, k):
    out = np.zeros_like(g)
    n = g.shape[axis]
    if abs(k) >= n:
        return out
    src = [slice(None)] * g.ndim
    dst = [slice(None)] * g.ndim
    if k > 0:
        dst[axis], src[axis] = slice(0, n - k), slice(k, n)
    else:
        dst[axis], src[axis] = slice(-k, n), slice(0, n + k)
    out[tuple(dst)] = g[tuple(src)]
    return out


@pytest.mark.parametrize("decomp,perm", [
    ((1, 2), None),
    ((0, 1), (2, 0, 1)),
    ((0, 2), (1, 2, 0)),
])
@pytest.mark.parametrize("shape", [(16, 12, 8), (10, 13, 8)])
def test_shift_matches_numpy(devices, decomp, perm, shape):
    topo = pa.Topology((4, 2), devices=devices)
    kw = {} if perm is None else {"permutation": pa.Permutation(*perm)}
    pen = pa.Pencil(topo, shape, decomp, **kw)
    g = np.random.default_rng(0).standard_normal(shape)
    u = pa.PencilArray.from_global(pen, g)
    for axis in range(3):
        for k in (1, -1, 3, -2):
            got = np.asarray(pa.gather(shift(u, axis, k)))
            np.testing.assert_allclose(got, np.roll(g, -k, axis=axis))
            gotz = np.asarray(pa.gather(shift(u, axis, k, boundary="zero")))
            np.testing.assert_allclose(gotz, _np_shift_zero(g, axis, k))


def test_shift_preserves_pencil_and_padding(devices):
    topo = pa.Topology((4, 2), devices=devices)
    pen = pa.Pencil(topo, (10, 12, 8), (0, 1))  # dim 0 padded 10 -> 12
    g = np.random.default_rng(1).standard_normal((10, 12, 8))
    u = pa.PencilArray.from_global(pen, g)
    v = shift(u, 0, 1)
    assert v.pencil == pen and v.extra_dims == ()
    # tail padding must stay zero-filled (the storage contract)
    tail = np.asarray(v.data[10:])
    np.testing.assert_array_equal(tail, np.zeros_like(tail))


def test_halo_hlo_budget(devices):
    """The halo exchange is GSPMD's partition of the shift: exactly one
    neighbor collective-permute per boundary crossing, and NEVER an
    all-gather (the MPI code's ghost-layer sends, compiler-derived)."""
    topo = pa.Topology((4, 2), devices=devices)
    pen = pa.Pencil(topo, (16, 16, 8), (0, 1))
    u = pa.PencilArray.zeros(pen)

    hlo = jax.jit(lambda d: shift(pa.PencilArray(pen, d), 0, 1).data) \
        .lower(u.data).compile().as_text()
    stats = collective_stats(hlo)
    assert "all-gather" not in stats and "all-to-all" not in stats
    assert stats.get("collective-permute", {}).get("count", 0) == 1

    hlo2 = jax.jit(
        lambda d: fd_laplacian(pa.PencilArray(pen, d), spacing=0.1).data) \
        .lower(u.data).compile().as_text()
    stats2 = collective_stats(hlo2)
    assert "all-gather" not in stats2 and "all-to-all" not in stats2
    # +-1 on each of the two decomposed dims
    assert stats2.get("collective-permute", {}).get("count", 0) <= 4


def test_padded_dim_halo_bytes(devices):
    """A periodic shift along a ceil-padded decomposed dim exchanges a
    THIN boundary layer, never full shards: the bulk roll moves |k|
    rows and the seam roll |k|+pad rows (roll shifts are congruent mod
    the padded extent), so the total collective-permute traffic is
    (2|k| + pad) rows — pinned in bytes here."""
    topo = pa.Topology((4,), devices=devices[:4])
    n, m = 10, 16            # dim 0: 10 over 4 -> ceil block 3, pad 2
    pen = pa.Pencil(topo, (n, m), (0,))
    u = pa.PencilArray.zeros(pen)
    k, pad = 1, pen.padded_global_shape[0] - n
    hlo = jax.jit(lambda d: shift(pa.PencilArray(pen, d), 0, k).data) \
        .lower(u.data).compile().as_text()
    stats = collective_stats(hlo)
    assert "all-gather" not in stats and "all-to-all" not in stats
    row_bytes = m * 4  # f32 rows
    got = stats.get("collective-permute", {}).get("bytes", 0)
    assert 0 < got <= (2 * k + pad) * row_bytes, (stats, pad)


def test_local_dim_shift_no_collectives(devices):
    topo = pa.Topology((4,), devices=devices[:4])
    pen = pa.Pencil(topo, (16, 12, 8), (0,))
    u = pa.PencilArray.zeros(pen)
    hlo = jax.jit(lambda d: shift(pa.PencilArray(pen, d), 2, 1).data) \
        .lower(u.data).compile().as_text()
    assert collective_stats(hlo) == {}


def test_fd_operators_match_numpy(devices):
    topo = pa.Topology((4, 2), devices=devices)
    shape = (12, 16, 9)
    pen = pa.Pencil(topo, shape, (0, 1))
    g = np.random.default_rng(2).standard_normal(shape)
    u = pa.PencilArray.from_global(pen, g)
    h = (0.5, 0.25, 2.0)

    d1 = np.asarray(pa.gather(diff(u, 1, order=1, spacing=h[1])))
    np.testing.assert_allclose(
        d1, (np.roll(g, -1, 1) - np.roll(g, 1, 1)) / (2 * h[1]), atol=1e-12)

    lap = np.asarray(pa.gather(fd_laplacian(u, spacing=h)))
    want = sum((np.roll(g, -1, d) - 2 * g + np.roll(g, 1, d)) / h[d] ** 2
               for d in range(3))
    np.testing.assert_allclose(lap, want, atol=1e-11)

    grads = fd_gradient(u, spacing=h)
    div = np.asarray(pa.gather(fd_divergence(grads, spacing=h)))
    wantg = [(np.roll(g, -1, d) - np.roll(g, 1, d)) / (2 * h[d])
             for d in range(3)]
    wantdiv = sum((np.roll(w, -1, d) - np.roll(w, 1, d)) / (2 * h[d])
                  for d, w in enumerate(wantg))
    np.testing.assert_allclose(div, wantdiv, atol=1e-11)


def test_fd_laplacian_converges(devices):
    """Second-order accuracy against the analytic Laplacian of a smooth
    periodic field (error ~ h^2: refining 16 -> 32 shrinks it ~4x)."""
    topo = pa.Topology((4,), devices=devices[:4])
    errs = []
    for n in (16, 32):
        h = 2 * np.pi / n
        x = np.arange(n) * h
        g = np.sin(x)[:, None] * np.cos(2 * x)[None, :]
        lap_true = -(1 + 4) * g  # eigvals -(1^2) and -(2^2)
        pen = pa.Pencil(topo, (n, n), (0,))
        u = pa.PencilArray.from_global(pen, g)
        lap = np.asarray(pa.gather(fd_laplacian(u, spacing=h)))
        errs.append(np.abs(lap - lap_true).max())
    assert errs[1] < errs[0] / 3.0


def test_differentiable(devices):
    topo = pa.Topology((4, 2), devices=devices)
    pen = pa.Pencil(topo, (8, 8, 8), (0, 1))
    g = np.random.default_rng(3).standard_normal((8, 8, 8))
    u = pa.PencilArray.from_global(pen, g)

    def loss(d):
        w = fd_laplacian(pa.PencilArray(pen, d), spacing=0.3)
        return jnp.sum(w.data ** 2)

    grad = jax.grad(loss)(u.data)
    # FD check along one coordinate
    eps = 1e-5
    e = np.zeros_like(g)
    e[2, 3, 4] = 1.0
    up = pa.PencilArray.from_global(pen, g + eps * e)
    dn = pa.PencilArray.from_global(pen, g - eps * e)
    fd = (loss(up.data) - loss(dn.data)) / (2 * eps)
    got = np.asarray(grad)[2, 3, 4]
    np.testing.assert_allclose(got, fd, rtol=2e-3)


def test_extra_dims_ride_along(devices):
    topo = pa.Topology((4,), devices=devices[:4])
    pen = pa.Pencil(topo, (8, 6), (0,))
    g = np.random.default_rng(4).standard_normal((8, 6, 3))
    u = pa.PencilArray.from_global(pen, g, extra_ndims=1)
    got = np.asarray(pa.gather(shift(u, 0, 2)))
    np.testing.assert_allclose(got, np.roll(g, -2, axis=0))


def test_decomposition_independent(devices):
    shape = (12, 10, 8)
    g = np.random.default_rng(5).standard_normal(shape)
    results = []
    for dims, decomp in [((8,), (0,)), ((4, 2), (0, 1)), ((2, 4), (1, 2))]:
        topo = pa.Topology(dims, devices=devices[:int(np.prod(dims))])
        pen = pa.Pencil(topo, shape, decomp)
        u = pa.PencilArray.from_global(pen, g)
        results.append(np.asarray(pa.gather(
            fd_laplacian(u, spacing=0.7, boundary="zero"))))
    np.testing.assert_allclose(results[0], results[1], atol=1e-12)
    np.testing.assert_allclose(results[0], results[2], atol=1e-12)


def test_validation_errors(devices):
    topo = pa.Topology((4,), devices=devices[:4])
    pen = pa.Pencil(topo, (8, 8), (0,))
    u = pa.PencilArray.zeros(pen)
    with pytest.raises(ValueError):
        shift(u, 5, 1)
    with pytest.raises(ValueError):
        shift(u, 0, 1, boundary="reflect")
    with pytest.raises(ValueError):
        diff(u, 0, order=3)
    with pytest.raises(ValueError):
        fd_gradient(u, spacing=(1.0,))
    with pytest.raises(ValueError):
        fd_divergence([u], spacing=1.0)
