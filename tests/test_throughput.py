"""Batched many-transform throughput mode (ISSUE 9).

The contracts under test:

* a ``PencilFFTPlan(batch=B)`` executes all B independent transforms
  through ONE shared exchange schedule — bit-identical to a per-sample
  loop and to ``vmap`` over the same plan, forward and backward, across
  slab/pencil topologies and c2c/r2c kinds;
* the compiled batched program's per-hop collective COUNT equals the
  unbatched plan's while its bytes are exactly xB (HLO-pinned — the
  latency-amortization claim, priced honestly by
  ``collective_costs``);
* ``decomposition="auto"`` enumerates slab + pencil topologies over
  the same devices, prices every candidate's full schedule with the
  validated cost model (hand-computed scores below), and builds the
  winner — including the Ring-vs-AllToAll resolution per hop and the
  drift correction of the PR-4 route planner;
* the verdict + batch are journaled (``plan.build`` schema v3), counted
  (``plan.decomposition{verdict=...}``) and rendered by the timeline.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.obs import drift as obs_drift
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.ops.fft import (
    PencilFFTPlan,
    _decomposition_candidates,
)
from pencilarrays_tpu.analysis import spmd
from pencilarrays_tpu.parallel.transpositions import AllToAll, Auto


def _rand_input(plan, extra_dims=None, seed=0):
    u = plan.allocate_input(extra_dims)
    host = np.random.default_rng(seed).standard_normal(
        tuple(u.data.shape)).astype(np.dtype(plan.dtype_physical))
    return pa.PencilArray(plan.input_pencil, jnp.asarray(host),
                          u.extra_dims)


# ---------------------------------------------------------------------------
# the batch knob
# ---------------------------------------------------------------------------


def test_batch_knob_defaults(devices):
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 6, 4), real=True, batch=4)
    assert plan.batch == 4 and plan.batch_dims == (4,)
    assert plan.allocate_input().extra_dims == (4,)
    assert plan.allocate_output().extra_dims == (4,)
    assert plan.allocate_input(()).extra_dims == ()  # explicit override
    # unbatched plans are unchanged
    plain = PencilFFTPlan(topo, (8, 6, 4), real=True)
    assert plain.batch is None and plain.batch_dims == ()
    assert plain.allocate_input().extra_dims == ()


@pytest.mark.parametrize("bad", [0, -1, 2.5, True, "4"])
def test_batch_knob_validation(devices, bad):
    topo = pa.Topology((2,), devices=devices[:2])
    with pytest.raises(ValueError, match="batch"):
        PencilFFTPlan(topo, (8, 6, 4), batch=bad)


def test_collective_costs_default_to_batch(devices):
    """A batched plan prices its amortization by default: bytes xB,
    count x1 vs the explicit per-sample price."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 6, 4), real=True, batch=4)
    per_sample = plan.collective_costs(())
    batched = plan.collective_costs()
    assert batched == plan.collective_costs((4,))
    for op, c in batched.items():
        assert c["count"] == per_sample[op]["count"]
        assert c["bytes"] == 4 * per_sample[op]["bytes"]


# ---------------------------------------------------------------------------
# bit-identity: batched == per-sample loop == vmap
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dims", [(4,), (2, 2)], ids=["slab", "pencil"])
@pytest.mark.parametrize("real", [False, True], ids=["c2c", "r2c"])
def test_batched_bit_identical_to_per_sample_loop(devices, dims, real):
    """ISSUE 9 acceptance: across slab/pencil x c2c/r2c x fwd/bwd, the
    batched path's every sample is BIT-identical to running the same
    plan unbatched on that sample."""
    n = int(np.prod(dims))
    topo = pa.Topology(dims, devices=devices[:n])
    B = 3
    plan = PencilFFTPlan(topo, (8, 6, 4), real=real, batch=B)
    u = _rand_input(plan, seed=17)

    uh = plan.forward(u)
    assert uh.extra_dims == (B,)
    back = plan.backward(uh)
    for b in range(B):
        ub = pa.PencilArray(plan.input_pencil, u.data[..., b])
        uhb = plan.forward(ub)
        assert jnp.array_equal(uhb.data, uh.data[..., b]), (dims, real, b)
        bb = plan.backward(uhb)
        assert jnp.array_equal(bb.data, back.data[..., b]), (dims, real, b)


def test_batched_bit_identical_to_vmap(devices):
    """The vmap cross-check: one jitted vmap over the unbatched chain
    equals the batched plan, fwd and bwd."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 6, 4), real=True, batch=3)
    u = _rand_input(plan, seed=3)
    uh = plan.forward(u)

    def fwd(d):
        return plan.forward(pa.PencilArray(plan.input_pencil, d)).data

    def bwd(d):
        return plan.backward(pa.PencilArray(plan.output_pencil, d)).data

    vm_f = jax.jit(jax.vmap(fwd, in_axes=-1, out_axes=-1))(u.data)
    assert jnp.array_equal(vm_f, uh.data)
    vm_b = jax.jit(jax.vmap(bwd, in_axes=-1, out_axes=-1))(uh.data)
    assert jnp.array_equal(vm_b, plan.backward(uh).data)


def test_batched_compiled_plan_roundtrip_and_donate(devices):
    """``compile()`` on a batched plan defaults to the batch, runs the
    whole chain as one program, and accepts input donation (the buffer
    is OFFERED to the program; XLA aliases it where dtypes allow — the
    donation accounting follows the batch with no shape/aliasing
    warnings)."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    plan = PencilFFTPlan(topo, (8, 6, 4), real=True, batch=3)
    cp = plan.compile(donate=True)
    assert cp.extra_dims == (3,) and cp.donate
    u = _rand_input(plan, seed=5)
    ref = plan.forward(u)
    uh = cp.forward(u)
    assert jnp.array_equal(uh.data, ref.data)
    assert isinstance(u.data.is_deleted(), bool)
    # the spectral->physical direction donates too, and round-trips
    back = plan.compile(donate=True).backward(uh)
    assert back.extra_dims == (3,)


# ---------------------------------------------------------------------------
# HLO pins: one program, count x1, bytes xB
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dims", [(4,), (2, 2)], ids=["slab", "pencil"])
@pytest.mark.parametrize("real", [False, True], ids=["c2c", "r2c"])
def test_batched_collectives_amortized_hlo_pinned(devices, dims, real):
    """ISSUE 9 acceptance: the compiled batched program issues EXACTLY
    as many collectives per hop as the unbatched one — the batch rides
    each hop's single collective (bytes xB) instead of multiplying
    launches — and the cost model predicts both programs exactly."""
    n = int(np.prod(dims))
    topo = pa.Topology(dims, devices=devices[:n])
    B = 4
    plan = PencilFFTPlan(topo, (8, 6, 4), real=real, batch=B)

    def measured(extra):
        # the ONE shared extractor (analysis/spmd.py)
        return spmd.trace_plan(plan, extra).stats()

    got1 = measured(())
    gotB = measured((B,))
    assert got1 == plan.collective_costs(())
    assert gotB == plan.collective_costs()
    for op, c in gotB.items():
        assert c["count"] == got1[op]["count"], (op, gotB, got1)
        assert c["bytes"] == B * got1[op]["bytes"], (op, gotB, got1)


# ---------------------------------------------------------------------------
# slab-vs-pencil auto-decomposition (hand-computed costs)
# ---------------------------------------------------------------------------

# The hand-computed configuration: a c2c (4,4,4) complex64 transform on
# 8 devices.  Extents 4 cannot feed 8 ranks, so the slab pays padding;
# the pencil grids divide evenly:
#
# * slab (8,): ONE hop (2,)->(1,).  Exchanged operand (logical extents,
#   split dim padded): dim0=4, dim1 padded 4->8, dim2 = 8/8 = 1
#   -> 32 elems x 8 B = 256 bytes; AllToAll = 1 collective.
#   Ring alternative: ceil-blocks of 1 -> G = 4 nonempty participants,
#   G-1 = 3 rounds of tile 32/8 = 4 elems -> 96 bytes, 3 collectives.
# * pencil (2,4) (and (4,2) symmetrically): TWO hops, each over a
#   divisible axis: 8 elems x 8 B = 64 bytes each -> 128 bytes total,
#   2 collectives, no padding anywhere.
#
# Auto(estimate)'s per-hop rule picks Ring for the slab hop iff
# 3*(L+32) < L + 7*32  <=>  L < 64 (L = latency_bytes); the schedule
# score is count*L + bytes.  Hence, hand-computed verdicts:
#
#   L = 128 KiB (default): slab = L+256 = 131328, pencil = 2L+128 =
#       262272 -> SLAB (one launch beats two at equal-ish bytes);
#   L = 64: slab = 64+256 = 320 (AllToAll: the Ring rule ties, 288
#       vs 288, and ties keep AllToAll), pencil = 128+128 = 256
#       -> PENCIL, (2,4) by the deterministic dims tie-break;
#   L = 16: slab hop resolves to RING: 3*16+96 = 144, pencil = 160
#       -> SLAB again, via Ring's ragged round elision.

_HAND = dict(shape=(4, 4, 4), nprocs=8)


def _auto_plan(devices, latency=None, **kw):
    topo = pa.Topology((_HAND["nprocs"],), devices=devices)
    method = Auto() if latency is None else Auto(latency_bytes=latency)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # slab candidates strand ranks
        return PencilFFTPlan(topo, _HAND["shape"], method=method,
                             decomposition="auto", **kw)


def _scores(plan):
    return {tuple(c["dims"]): c["score_bytes"]
            for c in plan.decomposition_verdict["candidates"]}


def test_auto_decomposition_default_latency_picks_slab(devices):
    plan = _auto_plan(devices)
    assert plan.topology.dims == (8,)
    v = plan.decomposition_verdict
    assert v["family"] == "slab" and v["winner"] == [8]
    L = Auto().latency_bytes
    assert _scores(plan) == {(8,): L + 256,
                             (2, 4): 2 * L + 128,
                             (4, 2): 2 * L + 128}


def test_auto_decomposition_picks_cheaper_pencil(devices):
    """ISSUE 9 acceptance: a mesh where slab and pencil disagree and
    the pencil schedule is provably cheaper — the plan builds on it."""
    plan = _auto_plan(devices, latency=64)
    assert plan.topology.dims == (2, 4)   # dims tie-break vs (4,2)
    v = plan.decomposition_verdict
    assert v["family"] == "pencil" and v["winner"] == [2, 4]
    assert _scores(plan) == {(8,): 64 + 256,
                             (2, 4): 2 * 64 + 128,
                             (4, 2): 2 * 64 + 128}
    # the winning plan actually computes: batched round trip on the
    # auto-built pencil grid matches numpy
    plan2 = _auto_plan(devices, latency=64, batch=2)
    u = _rand_input(plan2, seed=11)
    uh = plan2.forward(u)
    ref = np.fft.fftn(np.asarray(jax.device_get(pa.gather(u))),
                      axes=(0, 1, 2))
    got = np.asarray(jax.device_get(pa.gather(uh)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_auto_decomposition_ring_elision_in_scores(devices):
    """At a small latency toll the slab hop resolves to Ring (3 rounds
    among the 4 nonempty participants, 96 bytes) and beats the pencil —
    the pricer exploits the ragged-aware round elision per candidate."""
    plan = _auto_plan(devices, latency=16)
    assert plan.topology.dims == (8,)
    assert _scores(plan) == {(8,): 3 * 16 + 96,
                             (2, 4): 2 * 16 + 128,
                             (4, 2): 2 * 16 + 128}
    slab = next(c for c in plan.decomposition_verdict["candidates"]
                if c["dims"] == [8])
    assert slab["collectives"] == 3    # Ring rounds, not one AllToAll


def test_drift_correction_flips_decomposition(devices, monkeypatch):
    """The PR-4 discipline wired in: a trusted drift sample showing the
    slab hop running at HALF its modeled bytes-time flips the L=64
    verdict back to slab (256*0.5 + 64 = 192 < pencil 256)."""
    from pencilarrays_tpu.parallel import routing
    from pencilarrays_tpu.parallel.transpositions import _hop_label

    topo = pa.Topology((8,), devices=devices)
    slab_label = _hop_label(pa.Pencil(topo, (4, 4, 4), (2,)),
                            pa.Pencil(topo, (4, 4, 4), (1,)),
                            AllToAll(), jnp.complex64)
    monkeypatch.setattr(
        routing, "trusted_drift_hops",
        lambda: {slab_label: {"drift": 0.5, "source": "benchtime"}})
    plan = _auto_plan(devices, latency=64)
    assert plan.topology.dims == (8,)
    assert plan.decomposition_verdict["drift_corrected"] is True
    assert _scores(plan)[(8,)] == 64 + 128   # 256 bytes x 0.5 drift


def test_decomposition_scores_pipelined_like_cost_model(devices):
    """Review regression: a Pipelined plan method multiplies per-hop
    collective COUNT by its chunk factor on plain hops — the verdict's
    collectives/bytes must equal the HLO-pinned ``collective_costs`` of
    the built plan, never an unwrapped base's."""
    from pencilarrays_tpu.parallel.transpositions import Pipelined

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        topo = pa.Topology((8,), devices=devices)
        plan = PencilFFTPlan(topo, (16, 12, 20),
                             method=Pipelined(chunks=4),
                             decomposition="auto")
    win = next(c for c in plan.decomposition_verdict["candidates"]
               if tuple(c["dims"]) == plan.topology.dims)
    costs = plan.collective_costs()
    assert win["collectives"] == sum(v["count"] for v in costs.values())
    assert win["predicted_bytes"] == sum(v["bytes"]
                                         for v in costs.values())
    assert win["collectives"] > win["hops"]   # chunking really counted


def test_decomposition_forced_families_and_validation(devices):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        topo = pa.Topology((8,), devices=devices)
        slab = PencilFFTPlan(topo, (4, 4, 4), decomposition="slab")
        assert slab.topology.dims == (8,)
        pen = PencilFFTPlan(topo, (4, 4, 4), decomposition="pencil")
        assert pen.topology.dims in ((2, 4), (4, 2))
        assert pen.decomposition_verdict["family"] == "pencil"
    with pytest.raises(ValueError, match="decomposition"):
        PencilFFTPlan(topo, (4, 4, 4), decomposition="cube")
    # a rank-2 array admits no 2-D pencil (M < N)
    with pytest.raises(ValueError, match="no admissible"):
        PencilFFTPlan(topo, (8, 8), decomposition="pencil")
    # review regression: a REAL configuration error inside probe
    # construction propagates with its own message, never misattributed
    # to topology admissibility
    with pytest.raises(ValueError, match="transforms has 4 entries"):
        PencilFFTPlan(topo, (8, 8, 8),
                      transforms=("rfft", "fft", "fft", "fft"),
                      decomposition="auto")
    # fixed topology (decomposition=None) is untouched
    fixed = PencilFFTPlan(pa.Topology((2, 2), devices=devices[:4]),
                          (8, 6, 4))
    assert fixed.decomposition is None
    assert fixed.decomposition_verdict is None


def test_decomposition_candidates_enumeration():
    assert _decomposition_candidates(8, 3, "auto") == [
        (8,), (2, 4), (4, 2)]
    assert _decomposition_candidates(8, 3, "slab") == [(8,)]
    assert _decomposition_candidates(8, 3, "pencil") == [(2, 4), (4, 2)]
    assert _decomposition_candidates(8, 2, "auto") == [(8,)]  # N=2: no 2-D
    assert _decomposition_candidates(7, 3, "pencil") == []    # prime
    assert _decomposition_candidates(1, 3, "auto") == [(1,)]


def test_navier_stokes_decomposition_passthrough(devices):
    """The flagship model exposes the knob, and prices it at the
    traffic it actually sends: the (3,)-component state batches through
    every exchange, so the plan carries batch=3 and the verdict is
    scored at extra_dims=(3,) (review regression — an unbatched score
    can pick a grid that is cheaper only for traffic the model never
    sends)."""
    from pencilarrays_tpu.models import NavierStokesSpectral

    topo = pa.Topology((4,), devices=devices[:4])
    model = NavierStokesSpectral(topo, 8, decomposition="auto")
    assert model.plan.batch == 3
    assert model.plan.decomposition_verdict is not None
    assert model.plan.decomposition_verdict["mode"] == "auto"
    assert model.plan.decomposition_verdict["extra_dims"] == [3]
    assert tuple(model.plan.topology.dims) == tuple(
        model.plan.decomposition_verdict["winner"])


# ---------------------------------------------------------------------------
# r2c-aware packing
# ---------------------------------------------------------------------------


def test_r2c_schedule_moves_hermitian_half_bytes(devices):
    """Post-``rfft`` hops carry the shrunken spectrum: on (16,12,20) @
    (2,2) both hops run after the rfft stage, dim 0 is 16 -> 9, ceil-
    padded to 10 over the mesh axis — the r2c schedule moves EXACTLY
    10/16 of the c2c bytes at the same spectral dtype, batch included."""
    topo = pa.Topology((2, 2), devices=devices[:4])
    c2c = PencilFFTPlan(topo, (16, 12, 20), batch=4)
    r2c = PencilFFTPlan(topo, (16, 12, 20), real=True, batch=4)
    bc = c2c.collective_costs()
    br = r2c.collective_costs()
    assert bc == {"all-to-all": {"count": 2, "bytes": 4 * 15360}}
    assert br == {"all-to-all": {"count": 2, "bytes": 4 * 9600}}
    assert 9600 / 15360 == 10 / 16  # padded hermitian-half ratio
    # and the priced prediction IS what the batched program compiles to
    assert spmd.trace_plan(r2c, (4,)).stats() == br


def test_auto_decomposition_prices_r2c_schedules(devices):
    """Candidate scoring is r2c-aware: every candidate's predicted
    bytes for the r2c plan are strictly below the same candidate's c2c
    bytes (the probes price the shrunken post-rfft extents)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        topo = pa.Topology((8,), devices=devices)
        c2c = PencilFFTPlan(topo, (16, 12, 20), decomposition="auto")
        r2c = PencilFFTPlan(topo, (16, 12, 20), real=True,
                            decomposition="auto")
    by_dims = {tuple(c["dims"]): c["predicted_bytes"]
               for c in c2c.decomposition_verdict["candidates"]}
    for c in r2c.decomposition_verdict["candidates"]:
        assert c["predicted_bytes"] < by_dims[tuple(c["dims"])], c


# ---------------------------------------------------------------------------
# journaling: plan.build v3 fields, counter, timeline render
# ---------------------------------------------------------------------------


@pytest.fixture
def _clean_obs(monkeypatch):
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    monkeypatch.delenv("PENCILARRAYS_TPU_OBS_DIR", raising=False)
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    obs_drift.drift_tracker.reset()
    yield
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    obs_drift.drift_tracker.reset()


def test_plan_build_journals_batch_and_verdict(devices, tmp_path,
                                               monkeypatch, _clean_obs):
    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    # batch=3 at L=64 flips the verdict BACK to slab — the batch
    # multiplies bytes, which tips the slab hop's Auto resolution to
    # Ring (3 rounds x 96 B: score 3*64+288 = 480) under the pencil's
    # 2*64+3*128 = 512: the journaled verdict proves the batch feeds
    # the pricer
    plan = _auto_plan(devices, latency=64, batch=3)
    assert _scores(plan) == {(8,): 480, (2, 4): 512, (4, 2): 512}
    events = obs.read_journal(jdir)
    assert obs.lint_journal(events) == []
    builds = [e for e in events if e["ev"] == "plan.build"]
    assert len(builds) == 1      # probe candidates never journal
    # ...and probe SCORING never journals either: any auto.verdict
    # records belong to the BUILT plan's own hops (its collective_costs
    # resolves them for the plan.build payload), never to candidate
    # schedules that were priced and discarded (review regression —
    # the quiet resolve path)
    verdicts = [e for e in events if e["ev"] == "auto.verdict"]
    assert all("@(8,)" in e["config"] for e in verdicts), verdicts
    b = builds[0]
    assert b["v"] >= 3
    assert b["extra_dims"] == [3]
    assert b["decomposition"]["mode"] == "auto"
    assert b["decomposition"]["winner"] == [8]
    assert b["decomposition"]["family"] == "slab"
    assert b["decomposition"]["extra_dims"] == [3]
    assert b["topo"] == [8]
    # batched predicted costs ride the same record
    assert b["predicted_costs"] == plan.collective_costs()
    # the counter lands in snapshots
    snap = obs.snapshot()
    assert snap["counters"].get(
        "plan.decomposition{verdict=slab}") == 1.0
    # fixed-topology plans journal the fixed verdict + their batch
    PencilFFTPlan(pa.Topology((2, 2), devices=devices[:4]), (8, 6, 4),
                  batch=2)
    events = obs.read_journal(jdir)
    assert obs.lint_journal(events) == []
    fixed = [e for e in events if e["ev"] == "plan.build"][-1]
    assert fixed["extra_dims"] == [2]
    assert fixed["decomposition"] == {"mode": "fixed", "winner": [2, 2]}
    snap = obs.snapshot()
    assert snap["counters"].get(
        "plan.decomposition{verdict=fixed}") == 1.0


def test_timeline_renders_decomposition_verdict(devices, tmp_path,
                                                monkeypatch, _clean_obs):
    """``pa-obs timeline`` spells the verdict out (satellite: the
    decomposition decision is loud, like a route verdict)."""
    from pencilarrays_tpu.obs import timeline as tl

    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    _auto_plan(devices, latency=64, batch=3)
    merged = tl.merge_journals(jdir)
    text = tl.render(merged)
    assert "plan batch=3 decomp=auto:slab(8,)" in text


def test_pipelined_probe_construction_is_quiet(devices, tmp_path,
                                               monkeypatch, _clean_obs):
    """Review regression: with ``pipeline>1`` the probe plans' fused-hop
    construction resolves Auto bases — those resolutions must be quiet
    too, or discarded candidates journal phantom ``auto.verdict``
    records AND dedup-suppress the built plan's own verdict."""
    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        plan = PencilFFTPlan(pa.Topology((8,), devices=devices),
                             (16, 12, 20), method=Auto(latency_bytes=64),
                             pipeline=4, decomposition="auto")
    events = obs.read_journal(jdir)
    assert obs.lint_journal(events) == []
    win = f"@{plan.topology.dims}"
    verdicts = [e for e in events if e["ev"] == "auto.verdict"]
    assert verdicts, "the built plan's own resolution must still journal"
    assert all(win in e["config"] for e in verdicts), verdicts


def test_v3_schema_requires_plan_build_fields(_clean_obs):
    """A v3 ``plan.build`` without the throughput fields is a lint
    error; v2 records (pre-ISSUE-9 journals) stay clean."""
    base = {"v": 3, "ev": "plan.build", "run": "r", "proc": 0, "seq": 0,
            "t_wall": 0.0, "t_mono": 0.0, "step_idx": 0, "epoch": 0,
            "shape": [4], "transforms": ["fft"], "topo": [1],
            "pipeline": 1, "steps": []}
    errs = obs.lint_event(dict(base))
    assert any("extra_dims" in e for e in errs)
    assert any("decomposition" in e for e in errs)
    ok = dict(base, extra_dims=[], decomposition={"mode": "fixed"})
    assert obs.lint_event(ok) == []
    v2 = dict(base, v=2)
    assert obs.lint_event(v2) == []


# ---------------------------------------------------------------------------
# sweep smoke (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_throughput_sweep_smoke(devices):
    """The ``suite.py --throughput`` arm end to end at toy sizes: all
    three arms bit-identical, transforms/sec positive, verdict table
    and r2c ratio present."""
    from benchmarks.throughput import run_throughput_suite

    out = run_throughput_suite(devices, shape=(8, 8, 8), batches=(1, 4),
                               grids=((8, 8, 8),), k1=3, repeats=2)
    for B, entry in out["throughput"]["batches"].items():
        assert entry["bit_identical_batched_vs_loop"] is True
        assert entry["batched"]["transforms_per_s"] > 0
        assert entry["loop"]["transforms_per_s"] > 0
        if "error" not in entry["vmap"]:
            assert entry["bit_identical_batched_vs_vmap"] is True
    r2c = out["r2c_packing"]
    assert 0 < r2c["r2c_over_c2c"] < 1
    assert out["decomposition"], out
    for row in out["decomposition"]:
        assert row["verdict"]["winner"]
        assert row["measured"]
