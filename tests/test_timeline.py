"""Mesh observability plane (PR 7): cross-rank timeline merge under
hostile inputs, journal rotation, correlation keys, straggler
detection, mesh aggregation/fold, Prometheus escaping, and the
``pa-obs`` CLI.

The merge contract under test: wreckage — SIGKILL-torn final lines,
interleaved rotated segments, missing ranks, clock skew larger than a
hop, empty journals — degrades to *warnings*, never an exception and
never a silently dropped rank.
"""

import json
import os

import pytest

import pencilarrays_tpu as pa
from pencilarrays_tpu import obs
from pencilarrays_tpu.cluster.kv import FileKV
from pencilarrays_tpu.obs import aggregate as obs_agg
from pencilarrays_tpu.obs import correlate as obs_correlate
from pencilarrays_tpu.obs import drift as obs_drift
from pencilarrays_tpu.obs import events as obs_events
from pencilarrays_tpu.obs import metrics as obs_metrics
from pencilarrays_tpu.obs import straggler as obs_straggler
from pencilarrays_tpu.obs import timeline as obs_timeline
from pencilarrays_tpu.obs.__main__ import main as pa_obs_main


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv(obs.ENV_VAR, raising=False)
    monkeypatch.delenv("PENCILARRAYS_TPU_OBS_DIR", raising=False)
    monkeypatch.delenv("PENCILARRAYS_TPU_OBS_MAX_MB", raising=False)
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    obs_drift.drift_tracker.reset()
    yield
    obs_events._reset_for_tests()
    obs_metrics.registry.reset()
    obs_drift.drift_tracker.reset()


def _rec(rank, seq, ev, t, step=1, epoch=0, **fields):
    """A synthetic v2 journal record with every required field."""
    rec = {"v": 2, "ev": ev, "run": f"run-r{rank}", "proc": rank,
           "seq": seq, "t_wall": t, "t_mono": t, "step_idx": step,
           "epoch": epoch}
    rec.update(fields)
    return rec


def _hop(rank, seq, t, step=1, epoch=0, dispatch_s=0.001, hop="H"):
    return _rec(rank, seq, "hop", t, step, epoch, method="AllToAll",
                hop=hop, r=0, chunks=1, predicted_bytes=1024,
                dispatch_s=dispatch_s)


def _write_journal(d, rank, events, segment=None):
    os.makedirs(d, exist_ok=True)
    name = (f"journal.r{rank}.jsonl" if segment is None
            else f"journal.r{rank}.{segment}.jsonl")
    with open(os.path.join(d, name), "a") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return os.path.join(d, name)


# ---------------------------------------------------------------------------
# hostile merge inputs
# ---------------------------------------------------------------------------


def test_merge_torn_final_line_warns_not_throws(tmp_path):
    d = str(tmp_path)
    _write_journal(d, 0, [_hop(0, 1, 10.0), _hop(0, 2, 11.0)])
    with open(os.path.join(d, "journal.r0.jsonl"), "a") as f:
        f.write('{"v":2,"ev":"hop","proc":0,"t_wa')   # SIGKILL mid-append
    tl = obs_timeline.merge_journals(d)
    assert len(tl.events) == 2
    assert any("torn final line" in w for w in tl.warnings), tl.warnings
    assert obs.lint_journal(tl.events) == []


def test_merge_interleaved_rotated_segments(tmp_path):
    """Rotated segments read in rotation order, live file last — the
    rank's append order is reconstructed even though lexicographic
    filename order would interleave them wrongly (k=10 < k=2)."""
    d = str(tmp_path)
    seq = 0
    for k in list(range(1, 11)):
        seq += 1
        _write_journal(d, 0, [_hop(0, seq, 10.0)], segment=k)
    # identical wall times everywhere: the merge order must come from
    # the segment order alone (lexicographic would read k=10 before k=2)
    _write_journal(d, 0, [_hop(0, seq + 1, 10.0)])
    tl = obs_timeline.merge_journals(d)
    seqs = [e["seq"] for e in tl.events]
    assert seqs == sorted(seqs) and len(seqs) == 11


def test_merge_missing_rank_is_loud(tmp_path):
    d = str(tmp_path)
    _write_journal(d, 0, [_hop(0, 1, 10.0)])
    _write_journal(d, 2, [_hop(2, 1, 10.0)])
    tl = obs_timeline.merge_journals(d)
    assert tl.ranks == [0, 2]
    assert tl.missing_ranks == [1]
    assert any("rank 1: no journal" in w for w in tl.warnings), tl.warnings


def test_merge_empty_journal_keeps_rank(tmp_path):
    d = str(tmp_path)
    _write_journal(d, 0, [_hop(0, 1, 10.0)])
    open(os.path.join(d, "journal.r1.jsonl"), "w").close()
    tl = obs_timeline.merge_journals(d)
    assert tl.ranks == [0, 1]          # never silently dropped
    assert any("rank 1" in w and "empty" in w for w in tl.warnings)


def test_merge_corrects_clock_skew_larger_than_a_hop(tmp_path):
    """Rank 1's wall clock is an hour ahead; the shared epoch markers
    re-align the ranks, so the merged order interleaves the two ranks'
    step-1 work instead of putting all of rank 0 first."""
    d = str(tmp_path)
    skew = 3600.0
    marker = dict(reason="verdict:retry")
    _write_journal(d, 0, [
        _hop(0, 1, 100.0),
        _rec(0, 2, "guard.epoch", 101.0, epoch=1, **marker),
        _hop(0, 3, 102.0, epoch=1),
    ])
    _write_journal(d, 1, [
        _hop(1, 1, 100.2 + skew),
        _rec(1, 2, "guard.epoch", 101.1 + skew, epoch=1, **marker),
        _hop(1, 3, 102.3 + skew, epoch=1),
    ])
    tl = obs_timeline.merge_journals(d)
    assert tl.offset_method == "markers"
    assert tl.offsets[1] == pytest.approx(skew, abs=1.0)
    assert any("clock" in w for w in tl.warnings), tl.warnings
    order = [(e["proc"], e["seq"]) for e in tl.events]
    assert order == [(0, 1), (1, 1), (0, 2), (1, 2), (0, 3), (1, 3)]
    # without correction the hour of skew puts rank 0 entirely first
    raw = obs_timeline.merge_journals(d, correct_skew=False)
    assert [(e["proc"]) for e in raw.events] == [0, 0, 0, 1, 1, 1]


def test_merge_prefers_kv_clock_sync_records(tmp_path):
    d = str(tmp_path)
    _write_journal(d, 0, [_hop(0, 1, 100.0)])
    _write_journal(d, 1, [
        _rec(1, 1, "clock.sync", 160.0, ref_rank=0, offset_s=60.0,
             method="kv"),
        _hop(1, 2, 160.5),
    ])
    tl = obs_timeline.merge_journals(d)
    assert tl.offset_method == "clock.sync"
    assert tl.offsets[1] == pytest.approx(60.0)
    # rank 1's hop lands at corrected t=100.5: after rank 0's t=100
    assert [(e["proc"], e["ev"]) for e in tl.events][-1] == (1, "hop")


def test_merge_empty_directory(tmp_path):
    tl = obs_timeline.merge_journals(str(tmp_path))
    assert tl.events == [] and tl.ranks == []
    assert any("no journal files" in w for w in tl.warnings)
    # the trace of nothing is still valid trace JSON
    trace = obs_timeline.to_trace(tl)
    assert trace["traceEvents"] == []


# ---------------------------------------------------------------------------
# journal rotation
# ---------------------------------------------------------------------------


def test_journal_rotation_caps_and_reads_transparently(tmp_path,
                                                       monkeypatch):
    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    monkeypatch.setenv(obs_events.MAX_MB_VAR, "0.001")   # ~1 KiB cap
    for i in range(40):
        obs.record_event("run.stop", note="x" * 120)
    files = sorted(os.listdir(jdir))
    rotated = [f for f in files if f.startswith("journal.r0.")
               and f != "journal.r0.jsonl"]
    assert rotated, files
    # every segment honors the cap plus at most one record of slack
    for f in files:
        if f.startswith("journal.r0"):
            assert os.path.getsize(os.path.join(jdir, f)) < 2048
    # both readers see every record, in order, exactly once
    events = obs.read_journal(jdir)
    stops = [e for e in events if e["ev"] == "run.stop"]
    assert len(stops) == 40
    tl = obs_timeline.merge_journals(jdir)
    seqs = [e["seq"] for e in tl.events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert obs.lint_journal(tl.events) == []
    # rotation happens at record boundaries: no torn-line warnings
    assert not any("torn" in w for w in tl.warnings), tl.warnings


def test_no_rotation_without_cap(tmp_path, monkeypatch):
    jdir = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, jdir)
    for _ in range(40):
        obs.record_event("run.stop", note="x" * 120)
    assert sorted(os.listdir(jdir)) == ["journal.r0.jsonl"]


# ---------------------------------------------------------------------------
# correlation keys
# ---------------------------------------------------------------------------


def test_guarded_step_advances_step_idx(tmp_path, monkeypatch):
    from pencilarrays_tpu import guard

    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    base = obs_correlate.current_step()
    obs.record_event("run.stop")
    guard.guarded_step(lambda: 1, label="s")
    obs.record_event("run.stop")
    guard.guarded_step(lambda: 2, label="s")
    obs.record_event("run.stop")
    stops = [e for e in obs.read_journal() if e["ev"] == "run.stop"]
    assert [e["step_idx"] - base for e in stops] == [0, 1, 2]
    assert all(e["epoch"] == 0 for e in stops)
    assert obs.lint_journal(obs.read_journal()) == []


def test_plan_fingerprint_stamped_on_hops(tmp_path, monkeypatch):
    from pencilarrays_tpu.ops.fft import PencilFFTPlan

    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    topo = pa.Topology((2, 4))
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True)
    plan.forward(plan.allocate_input())
    events = obs.read_journal()
    hops = [e for e in events if e["ev"] == "hop"]
    assert hops and all(e.get("plan_fp") == plan._fingerprint()
                        for e in hops), hops
    build = next(e for e in events if e["ev"] == "plan.build")
    assert build["plan_fp"] == plan._fingerprint()


def test_route_plan_fp_prefixes_bundle_sha(tmp_path, monkeypatch):
    """The journal's ``plan_fp`` must be a PREFIX of the crash bundle's
    ``schedule_sha256`` for routed reshards too (one summary dict feeds
    both digests) — that prefix match is how a post-mortem ties a
    record to the compiled chain that was in flight."""
    from pencilarrays_tpu import guard
    from pencilarrays_tpu.guard import bundle as gb
    from pencilarrays_tpu.parallel.transpositions import Ring

    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    guard._reset_for_tests()
    guard.enable(str(tmp_path / "bundles"))
    try:
        topo = pa.Topology((2, 4))
        pen_a = pa.Pencil(topo, (12, 16, 10), (0, 1))
        pen_b = pa.Pencil(topo, (12, 16, 10), (1, 2))
        pa.reshard(pa.PencilArray.zeros(pen_a), pen_b, method=Ring())
        fp = obs_correlate.current_plan()
        shas = [p["schedule_sha256"] for p in gb.recent_plans()
                if p["kind"] == "reshard_route"]
        assert fp and any(s.startswith(fp) for s in shas), (fp, shas)
        obs.record_event("run.stop")
        ev = [e for e in obs.read_journal() if e["ev"] == "run.stop"][-1]
        assert ev["plan_fp"] == fp
    finally:
        guard.disable()


def test_explicit_payload_epoch_wins_over_stamp(tmp_path, monkeypatch):
    """An emitter that journals its OWN epoch (a consensus verdict's
    agreed value) must not have it rewritten by the global counter at
    write time — the stamp only fills in missing keys."""
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    obs.record_event("cluster.verdict", label="x", action="ok", epoch=7)
    ev = [e for e in obs.read_journal()
          if e["ev"] == "cluster.verdict"][-1]
    assert ev["epoch"] == 7
    assert "step_idx" in ev   # the other keys still stamped
    assert obs.lint_journal(obs.read_journal()) == []


def test_schema_v2_requires_correlation_keys():
    v2 = _hop(0, 1, 1.0)
    assert obs.lint_event(v2) == []
    missing = dict(v2)
    del missing["step_idx"]
    assert any("correlation key 'step_idx'" in e
               for e in obs.lint_event(missing))
    # v1 records (pre-PR-7 journals) stay lint-clean without the keys
    v1 = dict(v2, v=1)
    del v1["step_idx"], v1["epoch"]
    assert obs.lint_event(v1) == []


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------


def test_straggler_two_rank_floor():
    flags = obs_straggler.detect({0: {"H": 0.002}, 1: {"H": 0.302}})
    assert len(flags) == 1
    f = flags[0]
    assert f["rank"] == 1 and f["excess_s"] == pytest.approx(0.3)
    # microsecond jitter never flags anyone (the absolute floor)
    assert obs_straggler.detect({0: {"H": 0.0020}, 1: {"H": 0.0021}}) == []


def test_straggler_robust_z_on_larger_world():
    durs = {r: {"H": 0.010 + 0.0001 * r} for r in range(7)}
    durs[3] = {"H": 0.500}
    flags = obs_straggler.detect(durs)
    assert [f["rank"] for f in flags] == [3]
    # an outlier below the z threshold but above the floor: peers'
    # spread is wide, so the same excess is NOT an anomaly
    spread = {0: {"H": 0.1}, 1: {"H": 0.4}, 2: {"H": 0.7},
              3: {"H": 1.0}, 4: {"H": 1.3}}
    assert obs_straggler.detect(spread) == []


def test_straggler_single_rank_hop_skipped():
    assert obs_straggler.detect({0: {"H": 9.0}}) == []
    assert obs_straggler.detect({0: {"A": 9.0}, 1: {"B": 0.1}}) == []


def test_straggler_from_events_matches_live_rule():
    events = [_hop(0, i, 10.0 + i, dispatch_s=0.001) for i in range(3)]
    events += [_hop(1, i, 10.0 + i, dispatch_s=0.35 + 0.01 * i)
               for i in range(3)]
    flags = obs_straggler.detect_from_events(events)
    assert len(flags) == 1 and flags[0]["rank"] == 1
    # min is the representative: one slow outlier dispatch on a healthy
    # rank (compile, GC) must not flag it
    events = [_hop(0, 1, 10.0, dispatch_s=0.9),
              _hop(0, 2, 11.0, dispatch_s=0.001),
              _hop(1, 1, 10.0, dispatch_s=0.001)]
    assert obs_straggler.detect_from_events(events) == []


def test_straggler_windowed_catches_late_onset_degradation():
    """A rank that warms up fast and THEN degrades (thermal throttling
    mid-job) keeps its old all-time minimum — only the windowed mean
    between fold ticks (Δtotal/Δcount) can flag it."""
    def snap(count, total, mn):
        return {"drift": {"hops": {"H": {
            "source": "dispatch", "count": count, "total_s": total,
            "measured_s": mn}}}}

    # 1000 fast dispatches (1 ms), then 100 at 0.5 s on rank 1 only
    prev = {0: snap(1000, 1.0, 0.001), 1: snap(1000, 1.0, 0.001)}
    now = {0: snap(1100, 1.1, 0.001), 1: snap(1100, 51.0, 0.001)}
    # the all-time-min path is blind to it...
    assert obs_straggler.scan_snapshots(now) == []
    # ...the windowed path is not
    flags = obs_straggler.scan_snapshots(now, prev=prev)
    assert [f["rank"] for f in flags] == [1]
    assert flags[0]["duration_s"] == pytest.approx(0.5)
    # a hop with no new dispatches in the window is stale, not flagged
    idle = {0: snap(1100, 1.1, 0.001), 1: snap(1000, 1.0, 0.001)}
    assert obs_straggler.scan_snapshots(idle, prev=prev) == []


def test_scan_snapshots_emits_once_with_dedup(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    snaps = {0: {"drift": {"hops": {"H": {"measured_s": 0.001}}}},
             1: {"drift": {"hops": {"H": {"measured_s": 0.401}}}}}
    seen = set()
    flags = obs_straggler.scan_snapshots(snaps, emit=True, seen=seen)
    assert len(flags) == 1
    flags = obs_straggler.scan_snapshots(snaps, emit=True, seen=seen)
    assert len(flags) == 1   # still detected, but journaled only once
    events = [e for e in obs.read_journal()
              if e["ev"] == "cluster.straggler"]
    assert len(events) == 1
    assert events[0]["rank"] == 1
    assert events[0]["excess_s"] == pytest.approx(0.4)
    snap = obs.snapshot()
    assert snap["counters"]["cluster.stragglers{rank=1}"] == 1
    assert obs.lint_journal(obs.read_journal()) == []


# ---------------------------------------------------------------------------
# mesh aggregation
# ---------------------------------------------------------------------------


def _snap_with(counters=None, gauges=None, histograms=None, series=None):
    return {"counters": counters or {}, "gauges": gauges or {},
            "histograms": histograms or {}, "series": series or [],
            "drift": {"hops": {}}}


def test_fold_snapshots_merge_semantics():
    h0 = {"count": 2, "total": 1.0, "min": 0.4, "max": 0.6,
          "buckets_le_pow2": {"0": 2}}
    h1 = {"count": 3, "total": 6.0, "min": 0.1, "max": 4.0,
          "buckets_le_pow2": {"0": 1, "3": 2}}
    fold = obs_agg.fold_snapshots({
        0: _snap_with(counters={"c": 2}, gauges={"g": 1.0},
                      histograms={"h": h0}),
        2: _snap_with(counters={"c": 3}, gauges={"g": 5.0},
                      histograms={"h": h1}),
    })
    assert fold["ranks"] == [0, 2] and fold["missing_ranks"] == [1]
    assert fold["counters"]["c"] == 5
    assert fold["gauges"]["g"] == {"r0": 1.0, "r2": 5.0}
    h = fold["histograms"]["h"]
    assert h["count"] == 5 and h["total"] == pytest.approx(7.0)
    assert h["min"] == 0.1 and h["max"] == 4.0
    assert h["buckets_le_pow2"] == {"0": 3, "3": 2}
    assert h["mean"] == pytest.approx(1.4)


def test_mesh_prometheus_rank_labels_and_escaping():
    snaps = {
        0: _snap_with(series=[
            {"kind": "counter", "name": "c.x",
             "labels": {"fp": 'a"b\nc'}, "value": 2}]),
        1: _snap_with(series=[
            {"kind": "counter", "name": "c.x", "labels": {}, "value": 3},
            {"kind": "gauge", "name": "g", "labels": {}, "value": 7.5},
            {"kind": "histogram", "name": "h", "labels": {},
             "count": 4, "total": 2.0}]),
    }
    text = obs_agg.mesh_prometheus(snaps)
    assert 'pa_c_x_total{fp="a\\"b\\nc",rank="0"} 2' in text
    assert 'pa_c_x_total{rank="1"} 3' in text
    assert 'pa_g{rank="1"} 7.5' in text
    assert 'pa_h_count{rank="1"} 4' in text
    # label collision: a series-own `rank` label (the straggler's) must
    # survive the publisher label as exported_rank, not be clobbered
    collide = obs_agg.mesh_prometheus({0: _snap_with(series=[
        {"kind": "counter", "name": "cluster.stragglers",
         "labels": {"rank": "1"}, "value": 1}])})
    assert ('pa_cluster_stragglers_total'
            '{exported_rank="1",rank="0"} 1') in collide
    for line in text.splitlines():
        assert "\n" not in line   # no raw newline ever leaks into a value


def test_mesh_aggregator_publish_fold_over_filekv(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    kv = FileKV(str(tmp_path / "kv"))
    a0 = obs_agg.MeshAggregator(kv, 0, 2, cadence=60)
    a1 = obs_agg.MeshAggregator(kv, 1, 2, cadence=60)
    obs.counter("fold.me").inc(4)
    assert a0.publish_once() and a1.publish_once()
    fold = a0.fold_once(wait=True, timeout=5)
    assert fold is not None and fold["missing_ranks"] == []
    # both ranks published THIS process's registry: the fold sums them
    assert fold["counters"]["fold.me"] == 8
    jdir = str(tmp_path / "obs")
    assert os.path.exists(os.path.join(jdir, "mesh_metrics.json"))
    with open(os.path.join(jdir, "mesh_metrics.prom")) as f:
        prom = f.read()
    assert 'pa_fold_me_total{rank="0"} 4' in prom
    assert 'pa_fold_me_total{rank="1"} 4' in prom
    # non-rank-0 never folds
    assert a1.fold_once() is None
    # fold with a missing rank: a gap, not an exception
    kv.delete("pa/obsagg/r1")
    fold = a0.fold_once()
    assert fold["missing_ranks"] == [1]
    assert obs.lint_journal(obs.read_journal()) == []


def test_clock_beacon_offset_estimate(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    kv = FileKV(str(tmp_path / "kv"))
    a0 = obs_agg.MeshAggregator(kv, 0, 2, cadence=60)
    a1 = obs_agg.MeshAggregator(kv, 1, 2, cadence=60)
    assert a0.sync_clock_once() == 0.0
    # a first sighting has unknown staleness: NO sample — a stale
    # beacon read measures boot stagger, not skew (the review finding)
    assert a1.sync_clock_once() is None
    assert a0.sync_clock_once() == 0.0       # beacon refreshed
    off = a1.sync_clock_once()               # changed + recent: valid
    assert off is not None and 0.0 <= off < 1.0   # same host: ~delivery
    syncs = [e for e in obs.read_journal() if e["ev"] == "clock.sync"]
    assert len(syncs) == 1 and syncs[0]["ref_rank"] == 0
    assert syncs[0]["method"] == "kv"
    assert 0.0 <= syncs[0]["bound_s"] < 1.0


def test_merge_ignores_clock_sync_below_its_bound(tmp_path):
    """An exchanged offset smaller than its own measurement bound is
    exchange noise: 'correcting' an NTP-synced mesh by boot stagger
    would be worse than leaving the clocks alone."""
    d = str(tmp_path)
    _write_journal(d, 0, [_hop(0, 1, 100.0)])
    _write_journal(d, 1, [
        _rec(1, 1, "clock.sync", 100.3, ref_rank=0, offset_s=0.3,
             bound_s=0.4, method="kv"),
        _hop(1, 2, 100.4),
    ])
    tl = obs_timeline.merge_journals(d)
    assert tl.offset_method == "clock.sync"
    assert tl.offsets[1] == 0.0   # below its ±0.4 s bound: not applied


def test_clock_beacon_stale_read_never_samples(tmp_path, monkeypatch):
    """A beacon read after a long gap (boot stagger, coarse cadence)
    must not produce an offset: the staleness is unbounded."""
    monkeypatch.setenv(obs.ENV_VAR, str(tmp_path / "obs"))
    kv = FileKV(str(tmp_path / "kv"))
    a0 = obs_agg.MeshAggregator(kv, 0, 2, cadence=60)
    a1 = obs_agg.MeshAggregator(kv, 1, 2, cadence=60)
    a0.sync_clock_once()
    assert a1.sync_clock_once() is None
    a0.sync_clock_once()
    a1._last_beacon_read -= 10.0      # simulate a 10 s read gap
    assert a1.sync_clock_once() is None
    assert [e for e in obs.read_journal()
            if e["ev"] == "clock.sync"] == []


# ---------------------------------------------------------------------------
# prometheus exporter fixes (per-process registry)
# ---------------------------------------------------------------------------


def test_prometheus_escapes_hostile_label_values():
    obs.counter("evil.count", fp='say "hi"\nEOF').inc()
    text = obs.to_prometheus()
    line = next(l for l in text.splitlines() if "evil" in l and "#" not in l)
    assert line == 'pa_evil_count_total{fp="say \\"hi\\"\\nEOF"} 1'
    # the exposition grammar holds: every sample line still parses
    for l in text.splitlines():
        if l and not l.startswith("#"):
            assert " " in l and l.rsplit(" ", 1)[1]


def test_prometheus_emits_cluster_counters_and_drift_gauges():
    obs.counter("cluster.verdicts", action="retry").inc()
    obs.counter("cluster.stragglers", rank="1").inc()
    obs.gauge("cluster.epoch").set(2)
    obs_drift.drift_tracker.record("hopA", 100, 1.0, source="benchtime")
    obs_drift.drift_tracker.record("hopB", 300, 3.0, source="benchtime")
    text = obs.to_prometheus()
    assert 'pa_cluster_verdicts_total{action="retry"} 1' in text
    assert 'pa_cluster_stragglers_total{rank="1"} 1' in text
    assert "pa_cluster_epoch 2" in text
    assert 'pa_drift{hop="hopA",source="benchtime"} 1' in text
    assert 'pa_drift_fitted_bytes_per_s{class="device"} 100' in text


def test_snapshot_series_mirror_is_structured():
    obs.counter("s.c", method="Pipelined(chunks=2, base=AllToAll())").inc()
    snap = obs.snapshot()
    (s,) = [x for x in snap["series"] if x["name"] == "s.c"]
    assert s["kind"] == "counter" and s["value"] == 1
    # the label VALUE contains ',' and '=' — structurally intact here,
    # which is why the mesh fold never re-parses display keys
    assert s["labels"] == {
        "method": "Pipelined(chunks=2, base=AllToAll())"}


# ---------------------------------------------------------------------------
# pa-obs CLI
# ---------------------------------------------------------------------------


def test_cli_merge_lint_trace_roundtrip(tmp_path, capsys):
    d = str(tmp_path / "j")
    _write_journal(d, 0, [_hop(0, 1, 10.0),
                          _rec(0, 2, "guard.epoch", 11.0, epoch=1,
                               reason="verdict:retry")])
    _write_journal(d, 1, [_hop(1, 1, 10.1),
                          _rec(1, 2, "guard.epoch", 11.1, epoch=1,
                               reason="verdict:retry")])
    out = str(tmp_path / "merged.jsonl")
    assert pa_obs_main(["merge", d, "-o", out]) == 0
    with open(out) as f:
        merged = [json.loads(l) for l in f]
    assert len(merged) == 4
    assert pa_obs_main(["lint", d]) == 0
    capsys.readouterr()
    assert pa_obs_main(["timeline", d]) == 0
    text = capsys.readouterr().out
    assert "step 1 epoch 0" in text and "step 1 epoch 1" in text
    tr = str(tmp_path / "trace.json")
    assert pa_obs_main(["trace", d, "-o", tr]) == 0
    with open(tr) as f:
        trace = json.load(f)
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 1}
    names = {e["name"] for e in trace["traceEvents"]}
    assert "hop AllToAll" in names and "epoch 1" in names


def test_cli_lint_fails_on_schema_errors(tmp_path, capsys):
    d = str(tmp_path / "j")
    bad = _hop(0, 1, 10.0)
    del bad["method"]   # required hop field
    _write_journal(d, 0, [bad])
    assert pa_obs_main(["lint", d]) == 1
    assert "missing required field" in capsys.readouterr().out


def test_cli_drift_and_bundle(tmp_path, capsys, monkeypatch):
    d = str(tmp_path / "obs")
    monkeypatch.setenv(obs.ENV_VAR, d)
    obs_drift.drift_tracker.record("hopA", 100, 1.0, source="benchtime")
    obs.write_snapshot()
    assert pa_obs_main(["drift", d]) == 0
    out = capsys.readouterr().out
    assert "hopA" in out and "benchtime" in out
    # bundle summary + the merged-timeline pointer in the manifest
    from pencilarrays_tpu import guard
    from pencilarrays_tpu.guard.bundle import write_crash_bundle

    guard._reset_for_tests()   # earlier tests may have spent the cap
    guard.enable(str(tmp_path / "bundles"))
    try:
        obs.record_event("run.stop")
        path = write_crash_bundle("unit-test", "cli", error="boom")
        assert path is not None
        with open(os.path.join(path, "MANIFEST.json")) as f:
            man = json.load(f)
        assert man["timeline_cmd"].endswith(os.path.join(path, "journal"))
        assert pa_obs_main(["bundle", path]) == 0
        out = capsys.readouterr().out
        assert "unit-test" in out and "timeline:" in out
        # the bundled journal copy is itself a valid pa-obs target
        assert pa_obs_main(["lint", os.path.join(path, "journal")]) == 0
    finally:
        guard.disable()