"""Topology-shape sweep — the analog of the reference running its suite
under multiple MPI process counts (``runtests.jl:29-32``): the same
logical operations must hold for 1-D, 2-D and 3-D topologies, including
M = 3 decompositions of 4-D arrays and full M = N decomposition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    gather,
    reshard,
    transpose,
)
from pencilarrays_tpu import ops


def ref(shape, seed=0):
    return np.random.default_rng(seed).standard_normal(shape)


@pytest.mark.parametrize("dims", [(8,), (4, 2), (2, 4), (2, 2, 2)])
def test_transpose_under_every_topology(devices, dims):
    topo = Topology(dims)
    M = len(dims)
    N = M + 1
    shape = tuple([12, 10, 14, 9][:N])
    u = ref(shape)
    pen_a = Pencil(topo, shape, tuple(range(1, N)))
    # swap slot 0 decomposition to dim 0
    decomp_b = (0,) + tuple(range(2, N))
    pen_b = Pencil(topo, shape, decomp_b)
    x = PencilArray.from_global(pen_a, u)
    for m in (AllToAll(), Gspmd()):
        y = transpose(x, pen_b, method=m)
        np.testing.assert_array_equal(gather(y), u)
        back = transpose(y, pen_a, method=m)
        assert bool((back.data == x.data).all())


def test_3d_topology_4d_array_chain(devices):
    """M=3 decomposition of a 4-D array: x->y->z->w-style chain."""
    topo = Topology((2, 2, 2))
    shape = (10, 9, 8, 11)
    u = ref(shape, 1)
    pens = [
        Pencil(topo, shape, (1, 2, 3), permutation=Permutation(1, 2, 3, 0)),
        Pencil(topo, shape, (0, 2, 3)),
        Pencil(topo, shape, (0, 1, 3), permutation=Permutation(3, 0, 1, 2)),
        Pencil(topo, shape, (0, 1, 2)),
    ]
    x = PencilArray.from_global(pens[0], u)
    orig = x.data
    for pen in pens[1:]:
        x = transpose(x, pen)
        np.testing.assert_array_equal(gather(x), u)
    for pen in reversed(pens[:-1]):
        x = transpose(x, pen)
    assert bool((x.data == orig).all())


def test_full_decomposition_m_eq_n(devices):
    """M == N: every dim decomposed (``test/pencils.jl:523-542``);
    transposes between single-slot-differing configs still work via
    reshard (no dim stays local, so transpose() chains are impossible —
    exactly the reference's caveat)."""
    topo = Topology((2, 2, 2))
    shape = (6, 7, 9)
    u = ref(shape, 2)
    pen = Pencil(topo, shape, (0, 1, 2))
    x = PencilArray.from_global(pen, u)
    np.testing.assert_array_equal(gather(x), u)
    assert np.isclose(float(ops.sum(x)), u.sum())
    # reshard to a different axis assignment
    pen2 = Pencil(topo, shape, (2, 0, 1))
    y = reshard(x, pen2)
    np.testing.assert_array_equal(gather(y), u)


def test_reductions_3d_topology(devices):
    topo = Topology((2, 2, 2))
    shape = (7, 9, 11, 5)
    u = ref(shape, 3)
    pen = Pencil(topo, shape, (0, 2, 3), permutation=Permutation(2, 0, 3, 1))
    x = PencilArray.from_global(pen, u)
    assert np.isclose(float(ops.norm(x)), np.linalg.norm(u.ravel()))
    assert float(ops.maximum(x)) == pytest.approx(u.max())


def test_fft_3d_topology_4d_array(devices):
    from pencilarrays_tpu import PencilFFTPlan

    topo = Topology((2, 2, 2))
    shape = (8, 10, 6, 12)
    rng = np.random.default_rng(4)
    u = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    plan = PencilFFTPlan(topo, shape, dtype=jnp.complex128)
    x = PencilArray.from_global(plan.input_pencil, u)
    xh = plan.forward(x)
    np.testing.assert_allclose(gather(xh), np.fft.fftn(u), rtol=1e-9,
                               atol=1e-7)
    back = plan.backward(xh)
    np.testing.assert_allclose(gather(back), u, rtol=1e-10, atol=1e-10)
