"""Topology tests — parity with reference ``MPITopologies.jl`` semantics."""

import numpy as np
import pytest

from pencilarrays_tpu import Topology, dims_create


def test_dims_create():
    # MPI_Dims_create-style balanced factorizations (MPITopologies.jl:138-144)
    assert dims_create(8, 2) in ((4, 2),)
    assert dims_create(8, 3) == (2, 2, 2)
    assert dims_create(6, 2) == (3, 2)
    assert dims_create(7, 2) == (7, 1)
    assert dims_create(1, 3) == (1, 1, 1)
    assert dims_create(12, 2) == (4, 3)
    with pytest.raises(ValueError):
        dims_create(0, 2)


def test_topology_basic(devices):
    t = Topology((2, 4))
    assert t.dims == (2, 4)
    assert len(t) == 8
    assert t.ndims == 2
    assert t.axis_names == ("p1", "p2")
    assert t.mesh.axis_names == ("p1", "p2")
    assert t.subcomm(0) == "p1"
    assert t.subcomm(1) == "p2"


def test_topology_auto(devices):
    t = Topology.auto(2)
    assert sorted(t.dims, reverse=True) == [4, 2]
    t3 = Topology.auto(3)
    assert t3.dims == (2, 2, 2)


def test_ranks_coords_roundtrip(devices):
    t = Topology((2, 4))
    assert t.ranks.shape == (2, 4)
    for r in range(8):
        assert t.rank(t.coords(r)) == r
    assert t.coords(0) == (0, 0)
    assert t.coords(7) == (1, 3)
    # row-major like MPI Cartesian default
    assert t.rank((1, 0)) == 4


def test_topology_errors(devices):
    with pytest.raises(ValueError):
        Topology((3, 4))  # 12 != 8 devices
    with pytest.raises(ValueError):
        Topology((2, 2))  # 4 != 8: exact match required (MPITopologies.jl:152-156)
    with pytest.raises(ValueError):
        Topology((2, 2), devices=devices[:4], axis_names=("a",))
    with pytest.raises(ValueError):
        Topology((2, 2), devices=devices[:4], axis_names=("a", "a"))


def test_topology_eq(devices):
    a, b = Topology((2, 4)), Topology((2, 4))
    assert a == b and hash(a) == hash(b)
    assert a != Topology((4, 2))
    # same dims, different axis names -> different (subcomm identity differs)
    assert a != Topology((2, 4), axis_names=("x", "y"))


def test_subset_of_devices(devices):
    t = Topology((2, 2), devices=devices[:4])
    assert len(t) == 4
    assert t.device((0, 0)).id == devices[0].id


def test_from_mesh_validates(devices):
    """from_mesh applies constructor-grade validation (ADVICE r1 weak #8):
    Explicit axis types would fail later with an opaque shard_map error."""
    import numpy as np
    from jax.sharding import Mesh

    from pencilarrays_tpu.utils.jaxcompat import AxisType

    dev = np.array(devices, dtype=object).reshape(2, 4)
    if AxisType is None:
        # pre-AxisType jax: every axis is implicitly Auto; from_mesh
        # must accept a plain mesh (nothing Explicit to reject)
        t = Topology.from_mesh(Mesh(dev, ("a", "b")))
        assert t.dims == (2, 4)
        return
    ok = Mesh(dev, ("a", "b"), axis_types=(AxisType.Auto,) * 2)
    t = Topology.from_mesh(ok)
    assert t.dims == (2, 4)
    bad = Mesh(dev, ("a", "b"),
               axis_types=(AxisType.Explicit, AxisType.Auto))
    with pytest.raises(ValueError, match="Auto axis types"):
        Topology.from_mesh(bad)
