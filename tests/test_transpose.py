"""Transpose engine tests — mirrors the reference sweep in
``test/transpose.jl``: every method x permutation x decomposition
combination validated against gathered ground truth
(``compare_distributed_arrays``, ``test/transpose.jl:6-22``), plus
round-trip bit-identity (``test/transpose.jl:60``), the x->y->z chain
(``:48-58``), and unsorted decomposition dims (#57, ``:69-74``)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pipelined,
    Ring,
    Pencil,
    PencilArray,
    Permutation,
    Topology,
    Transposition,
    gather,
    reshard,
    transpose,
)

METHODS = [AllToAll(), Gspmd(), Ring()]


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


def global_ref(shape, extra=(), dtype=np.float64):
    n = int(np.prod(shape + extra, dtype=int))
    return (np.arange(n, dtype=dtype).reshape(shape + extra) + 1.0) / 3.0


@pytest.mark.parametrize("method", METHODS)
@pytest.mark.parametrize("shape", [(16, 16, 16), (42, 31, 29), (7, 12, 13)])
def test_x_to_y_ground_truth(topo, method, shape):
    u = global_ref(shape)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = pen_x.replace(decomp_dims=(0, 2))
    x = PencilArray.from_global(pen_x, u)
    y = transpose(x, pen_y, method=method)
    assert y.pencil == pen_y
    np.testing.assert_array_equal(gather(y), u)


@pytest.mark.parametrize("method", METHODS)
@pytest.mark.parametrize(
    "perm_x,perm_y",
    [
        (None, None),
        (None, Permutation(1, 0, 2)),
        (Permutation(2, 0, 1), Permutation(1, 2, 0)),
    ],
)
def test_permutation_combinations(topo, method, perm_x, perm_y):
    shape = (15, 14, 13)
    u = global_ref(shape)
    pen_x = Pencil(topo, shape, (1, 2), permutation=perm_x)
    pen_y = Pencil(topo, shape, (0, 2), permutation=perm_y)
    x = PencilArray.from_global(pen_x, u)
    y = transpose(x, pen_y, method=method)
    np.testing.assert_array_equal(gather(y), u)


@pytest.mark.parametrize("method", METHODS)
def test_xyz_cycle_bit_identity(topo, method):
    """x->y->z->y->x round trip must be bit-identical
    (``test/transpose.jl:44-60``)."""
    shape = (14, 21, 19)
    u = global_ref(shape)
    pen_x = Pencil(topo, shape, (1, 2), permutation=None)
    pen_y = Pencil(topo, shape, (0, 2), permutation=Permutation(1, 0, 2))
    pen_z = Pencil(topo, shape, (0, 1), permutation=Permutation(2, 1, 0))
    u1 = PencilArray.from_global(pen_x, u)
    u2 = transpose(u1, pen_y, method=method)
    u3 = transpose(u2, pen_z, method=method)
    np.testing.assert_array_equal(gather(u3), u)
    # back
    v2 = transpose(u3, pen_y, method=method)
    v1 = transpose(v2, pen_x, method=method)
    # bit identity: pure data movement, no arithmetic
    assert bool((v1.data == u1.data).all())
    np.testing.assert_array_equal(gather(v1), u)


@pytest.mark.parametrize("method", METHODS)
def test_unsorted_decomp_dims(topo, method):
    """Unsorted decompositions (#57, ``test/transpose.jl:69-74``)."""
    shape = (11, 12, 13)
    u = global_ref(shape)
    pen_a = Pencil(topo, shape, (2, 1))
    pen_b = Pencil(topo, shape, (2, 0))
    x = PencilArray.from_global(pen_a, u)
    y = transpose(x, pen_b, method=method)
    np.testing.assert_array_equal(gather(y), u)


@pytest.mark.parametrize("method", METHODS)
def test_extra_dims_ride_along(topo, method):
    shape = (10, 11, 12)
    u = global_ref(shape, extra=(3, 2))
    pen_x = Pencil(topo, shape, (1, 2), permutation=Permutation(2, 0, 1))
    pen_y = Pencil(topo, shape, (0, 2))
    x = PencilArray.from_global(pen_x, u)
    y = transpose(x, pen_y, method=method)
    assert y.extra_dims == (3, 2)
    np.testing.assert_array_equal(gather(y), u)


def test_same_decomp_permutation_only(topo):
    """Decomposition unchanged, permutation changes: local permute only
    (``Transpositions.jl:214-271``)."""
    shape = (9, 10, 11)
    u = global_ref(shape)
    pen_a = Pencil(topo, shape, (1, 2))
    pen_b = pen_a.replace(permutation=Permutation(2, 1, 0))
    x = PencilArray.from_global(pen_a, u)
    y = transpose(x, pen_b)
    assert y.pencil == pen_b
    np.testing.assert_array_equal(gather(y), u)
    # and identical pencils: passthrough
    z = transpose(x, pen_a)
    assert bool((z.data == x.data).all())


def test_incompatible(topo, devices):
    shape = (8, 8, 8)
    pen_x = Pencil(topo, shape, (1, 2))
    x = PencilArray.zeros(pen_x)
    # both slots differ -> must chain (Transpositions.jl:182-199)
    pen_bad = Pencil(topo, shape, (0, 1))
    with pytest.raises(ValueError, match="more than one slot"):
        transpose(x, pen_bad)
    # different global shape
    with pytest.raises(ValueError, match="global shapes"):
        transpose(x, Pencil(topo, (8, 8, 9), (1, 2)))
    # different topology
    topo2 = Topology((4, 2))
    with pytest.raises(ValueError, match="topologies"):
        transpose(x, Pencil(topo2, shape, (1, 2)))


def test_reshard_multi_slot(topo):
    """reshard() handles what transpose() refuses."""
    shape = (12, 10, 14)
    u = global_ref(shape)
    pen_a = Pencil(topo, shape, (1, 2))
    pen_b = Pencil(topo, shape, (0, 1), permutation=Permutation(2, 0, 1))
    x = PencilArray.from_global(pen_a, u)
    y = reshard(x, pen_b)
    np.testing.assert_array_equal(gather(y), u)


@pytest.mark.parametrize("method", METHODS)
def test_transpose_under_jit(topo, method):
    """The whole exchange must be traceable & fusable."""
    shape = (16, 12, 8)
    u = global_ref(shape)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = pen_x.replace(decomp_dims=(0, 2))

    @jax.jit
    def step(a):
        b = transpose(a, pen_y, method=method)
        return b.map(lambda d: d * 2.0)

    x = PencilArray.from_global(pen_x, u)
    y = step(x)
    assert isinstance(y, PencilArray)
    np.testing.assert_array_equal(gather(y), u * 2.0)


def test_transposition_object_api(topo):
    shape = (8, 12, 16)
    u = global_ref(shape)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = pen_x.replace(decomp_dims=(0, 2))
    x = PencilArray.from_global(pen_x, u)
    t = Transposition(pen_y, x)
    assert t.dim == 0  # differing slot
    y = t.execute()
    t.waitall()
    np.testing.assert_array_equal(gather(y), u)


@pytest.mark.parametrize("method", METHODS)
def test_4d_two_dim_decomposition(topo, method):
    """4D array, M=2 decomposition with permutation (cf.
    ``test/pencils.jl:341-357``), complex dtype."""
    shape = (6, 7, 8, 9)
    n = int(np.prod(shape))
    u = (np.arange(n) + 1j * np.arange(n)).reshape(shape).astype(np.complex64)
    pen_a = Pencil(topo, shape, (1, 3), permutation=Permutation(3, 0, 1, 2))
    pen_b = Pencil(topo, shape, (2, 3))
    x = PencilArray.from_global(pen_a, u)
    y = transpose(x, pen_b, method=method)
    np.testing.assert_array_equal(gather(y), u)


def test_1d_slab_topology(devices):
    """Slab (1-D) decomposition (``test/pencils.jl:483-520``)."""
    topo1 = Topology((8,))
    shape = (21, 17, 14)
    u = global_ref(shape)
    for d_in, d_out in [((0,), (1,)), ((1,), (2,)), ((2,), (0,))]:
        pen_a = Pencil(topo1, shape, d_in)
        pen_b = Pencil(topo1, shape, d_out)
        x = PencilArray.from_global(pen_a, u)
        for m in METHODS:
            y = transpose(x, pen_b, method=m)
            np.testing.assert_array_equal(gather(y), u)


def test_ring_ragged_skips_empty_rounds(topo):
    """Ragged-aware Ring: with n=9 over P=4 (ceil blocks of 3 -> only 3
    nonempty blocks) the ring runs G-1=2 ppermute rounds instead of P-1=3,
    bit-identical to AllToAll.  The reference sends exact intersection
    ranges (Transpositions.jl:383-389); under SPMD static shapes the
    achievable analog is statically skipping structurally-empty rounds."""
    import re

    shape = (9, 16, 9)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (1, 0))  # differ in slot 1: P=4 axis
    rng = np.random.default_rng(40)
    u = rng.standard_normal(shape)
    x = PencilArray.from_global(pen_x, u)

    y_ring = transpose(x, pen_y, method=Ring())
    y_a2a = transpose(x, pen_y, method=AllToAll())
    np.testing.assert_array_equal(gather(y_ring), u)
    np.testing.assert_array_equal(np.asarray(y_ring.data),
                                  np.asarray(y_a2a.data))  # incl. padding

    hlo = jax.jit(
        lambda d: transpose(PencilArray(pen_x, d), pen_y,
                            method=Ring()).data
    ).lower(x.data).compile().as_text()
    n_pp = len(re.findall(r" collective-permute\(", hlo))
    assert n_pp == 2, n_pp  # G-1, not P-1


# -- Pipelined (chunked) exchange -----------------------------------------


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex64,
                                   np.complex128, np.int32])
@pytest.mark.parametrize("shape", [(16, 16, 16), (9, 16, 9)])
def test_pipelined_bit_identity_even_and_ragged(topo, shape, dtype):
    """Pipelined(K) is BIT-identical to AllToAll — padding content
    included — for even and ragged shards across dtypes: chunking along
    an exchange-untouched dim is pure data movement."""
    shape_arr = global_ref(shape, dtype=np.float64)
    u = (shape_arr + (1j * shape_arr if np.issubdtype(dtype,
                                                      np.complexfloating)
         else 0)).astype(dtype)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2))
    x = PencilArray.from_global(pen_x, u)
    y_ref = transpose(x, pen_y, method=AllToAll())
    for K in (2, 4, 8):
        y = transpose(x, pen_y, method=Pipelined(chunks=K))
        np.testing.assert_array_equal(np.asarray(y.data),
                                      np.asarray(y_ref.data))
    np.testing.assert_array_equal(gather(y), u)


def test_pipelined_k1_is_all_to_all(topo):
    """chunks=1 degenerates exactly to the base method (one monolithic
    exchange — same compiled collective profile)."""
    import re

    shape = (16, 12, 8)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2))
    x = PencilArray.zeros(pen_x)

    def n_a2a(method):
        hlo = jax.jit(
            lambda d: transpose(PencilArray(pen_x, d), pen_y,
                                method=method).data
        ).lower(x.data).compile().as_text()
        return len(re.findall(r" all-to-all\(", hlo))

    assert n_a2a(Pipelined(chunks=1)) == 1
    assert n_a2a(Pipelined(chunks=4)) == 2  # chunk dim extent 8/4 = 2


def test_pipelined_ring_base_bit_identity(topo):
    """The ragged-aware Ring exchange reused per chunk stays
    bit-identical (its closure is shape-polymorphic along the chunked
    dim)."""
    shape = (9, 16, 9)  # ragged on both exchange dims
    u = global_ref(shape)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (1, 0))
    x = PencilArray.from_global(pen_x, u)
    y_ref = transpose(x, pen_y, method=AllToAll())
    y = transpose(x, pen_y, method=Pipelined(chunks=3, base=Ring()))
    np.testing.assert_array_equal(np.asarray(y.data),
                                  np.asarray(y_ref.data))


def test_pipelined_round_trip_identity(topo):
    shape = (14, 21, 19)
    u = global_ref(shape)
    pen_x = Pencil(topo, shape, (1, 2), permutation=None)
    pen_y = Pencil(topo, shape, (0, 2), permutation=Permutation(1, 0, 2))
    x = PencilArray.from_global(pen_x, u)
    y = transpose(x, pen_y, method=Pipelined(chunks=4))
    back = transpose(y, pen_x, method=Pipelined(chunks=4))
    assert bool((back.data == x.data).all())  # bit identity, incl. padding


def test_pipelined_validation():
    with pytest.raises(ValueError, match="positive int"):
        Pipelined(chunks=0)
    with pytest.raises(ValueError, match="base"):
        Pipelined(chunks=2, base=Gspmd())


def test_pipelined_extra_dims_chunk_axis(topo):
    """Extra dims are chunk-axis candidates too; here the extra dim has
    the largest local extent, so it carries the chunking (and the data
    still rides along bit-identically)."""
    shape = (10, 11, 12)
    u = global_ref(shape, extra=(6,))
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (0, 2))
    x = PencilArray.from_global(pen_x, u)
    y_ref = transpose(x, pen_y, method=AllToAll())
    y = transpose(x, pen_y, method=Pipelined(chunks=2))
    assert y.extra_dims == (6,)
    np.testing.assert_array_equal(np.asarray(y.data),
                                  np.asarray(y_ref.data))


@pytest.mark.parametrize("n_ab", [(5, 9), (13, 9), (9, 13), (6, 2), (1, 9)])
def test_ring_ragged_asymmetric_bit_identity(topo, n_ab):
    """Asymmetric raggedness (S_a != S_b, and G == P with S_b < P):
    Ring must stay bit-identical to AllToAll including padding content."""
    n_a, n_b = n_ab
    shape = (n_b, 16, n_a)
    pen_x = Pencil(topo, shape, (1, 2))
    pen_y = Pencil(topo, shape, (1, 0))  # exchange over the P=4 axis
    u = np.random.default_rng(41).standard_normal(shape)
    x = PencilArray.from_global(pen_x, u)
    y_ring = transpose(x, pen_y, method=Ring())
    y_a2a = transpose(x, pen_y, method=AllToAll())
    np.testing.assert_array_equal(gather(y_ring), u)
    np.testing.assert_array_equal(np.asarray(y_ring.data),
                                  np.asarray(y_a2a.data))
