"""Timer subsystem and permuted-index iterator tests (reference:
TimerOutputs integration, ``Pencils.jl:191``; PermutedIndices semantics,
``PermutedIndices.jl:17-93``; iteration-order invariants,
``test/pencils.jl:244-278``)."""

import numpy as np
import pytest

from pencilarrays_tpu import (
    NO_PERMUTATION,
    Pencil,
    PencilArray,
    PermutedCartesianIndices,
    PermutedLinearIndices,
    Permutation,
    TimerOutput,
    Topology,
    disable_debug_timings,
    enable_debug_timings,
    transpose,
)


def test_permuted_cartesian_walks_memory_order():
    shape = (2, 3, 4)
    perm = Permutation(2, 0, 1)  # memory dims = (d2, d0, d1)
    it = PermutedCartesianIndices(shape, perm)
    assert len(it) == 24
    seen = list(it)
    # every logical index exactly once
    assert sorted(seen) == sorted(np.ndindex(*shape))
    # memory-contiguity: consecutive elements advance the LAST memory dim
    # (logical dim 1) fastest
    assert seen[0] == (0, 0, 0)
    assert seen[1] == (0, 1, 0)  # memory dims (d2,d0,d1): d1 fastest
    # indexing matches iteration
    assert it[1] == seen[1]
    assert it[23] == seen[23]


def test_permuted_linear_roundtrip():
    shape = (3, 4, 5)
    perm = Permutation(1, 2, 0)
    lin = PermutedLinearIndices(shape, perm)
    cart = PermutedCartesianIndices(shape, perm)
    for n in (0, 7, 59):
        assert lin[cart[n]] == n
    # agreement with raw memory-order array walking
    arr = np.arange(np.prod(shape)).reshape(perm.apply(shape))
    for n, logical in enumerate(cart):
        assert arr[perm.apply(logical)] == n


def test_identity_permutation_iteration():
    it = PermutedCartesianIndices((2, 2), NO_PERMUTATION)
    assert list(it) == [(0, 0), (0, 1), (1, 0), (1, 1)]


def test_timer_hierarchy():
    t = TimerOutput("test")
    enable_debug_timings()
    try:
        with t("outer"):
            with t("inner"):
                pass
            with t("inner"):
                pass
        rep = t.report()
        assert "outer" in rep and "inner" in rep
        assert t._root.children["outer"].ncalls == 1
        assert t._root.children["outer"].children["inner"].ncalls == 2
    finally:
        disable_debug_timings()


def test_timer_attached_to_pencil(devices):
    topo = Topology((2, 4))
    timer = TimerOutput("pencil")
    pen_x = Pencil(topo, (8, 8, 8), (1, 2), timer=timer)
    pen_y = Pencil(topo, (8, 8, 8), (0, 2), timer=timer)
    x = PencilArray.zeros(pen_x)
    enable_debug_timings()
    try:
        transpose(x, pen_y)
    finally:
        disable_debug_timings()
    assert timer._root.children["transpose!"].ncalls == 1
    # disabled by default: no recording
    timer.reset()
    transpose(x, pen_y)
    assert "transpose!" not in timer._root.children


def test_astype_real_imag(devices):
    import jax.numpy as jnp

    topo = Topology((2, 4))
    pen = Pencil(topo, (8, 8, 8), (1, 2))
    x = PencilArray.zeros(pen, dtype=jnp.complex64)
    assert x.astype(jnp.complex128).dtype == jnp.complex128
    assert x.real.dtype == jnp.float32
    assert x.imag.dtype == jnp.float32
    assert x.conj().dtype == jnp.complex64
    y = x.copy()
    assert y.pencil == x.pencil


def test_extrema(devices):
    from pencilarrays_tpu import ops

    topo = Topology((2, 4))
    pen = Pencil(topo, (9, 11, 13), (1, 2))
    u = np.random.default_rng(0).standard_normal((9, 11, 13))
    x = PencilArray.from_global(pen, u)
    lo, hi = ops.extrema(x)
    assert float(lo) == pytest.approx(u.min())
    assert float(hi) == pytest.approx(u.max())
