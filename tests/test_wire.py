"""Reduced-precision wire format (ISSUE 13): pack/unpack contracts,
halved HLO-pinned bytes, wire-aware pricing through Auto/router/guard,
the typed ``WirePrecisionError`` tolerance contract, and the dispatch
log's wire-byte certification.

The acceptance pins live here: ``wire_dtype=None`` is BIT-IDENTICAL to
the historical behavior; ``wire_dtype="bf16"`` halves priced AND
measured exchange bytes; out-of-tolerance drift on a wire hop raises
typed — never a silent wrong answer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pencilarrays_tpu import (
    AllToAll,
    Gspmd,
    Pencil,
    PencilArray,
    PencilFFTPlan,
    Ring,
    Topology,
    gather,
    guard,
    reshard,
    transpose,
    transpose_cost,
)
from pencilarrays_tpu.analysis import spmd
from pencilarrays_tpu.guard import IntegrityError, WirePrecisionError
from pencilarrays_tpu.guard.integrity import check_hop_probes, probes_match
from pencilarrays_tpu.parallel import wire
from pencilarrays_tpu.parallel.transpositions import (
    Auto,
    Pipelined,
    _method_label,
    resolve_method,
    with_wire,
)


@pytest.fixture
def topo(devices):
    return Topology((2, 4))


@pytest.fixture
def hop(topo):
    pin = Pencil(topo, (16, 12, 20), (1, 2))
    pout = Pencil(topo, (16, 12, 20), (0, 2))
    return pin, pout


# ---------------------------------------------------------------------------
# wire.py unit contracts
# ---------------------------------------------------------------------------


def test_canonical_wire_dtype_spellings():
    for spelling in ("bf16", "bfloat16", jnp.bfloat16):
        assert wire.canonical_wire_dtype(spelling) == "bf16"
    for spelling in ("f16", "float16", "half", jnp.float16, np.float16):
        assert wire.canonical_wire_dtype(spelling) == "f16"
    assert wire.canonical_wire_dtype(None) is None
    with pytest.raises(ValueError):
        wire.canonical_wire_dtype("fp8")
    with pytest.raises(ValueError):
        wire.canonical_wire_dtype(np.float32)


def test_pack_unpack_real_roundtrip_quantization_bound():
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (7, 5)).astype(np.float32))
    for w, eps in (("bf16", 2.0 ** -8), ("f16", 2.0 ** -11)):
        p = wire.pack(x, w)
        # the wire carries the raw 16-bit pattern (u16 — backends
        # without native bf16 collectives would widen a float wire)
        assert p.dtype == jnp.uint16 and p.shape == x.shape
        back = wire.unpack(p, x.dtype, w)
        assert back.dtype == x.dtype
        assert float(jnp.max(jnp.abs(back - x))) <= eps * float(
            jnp.max(jnp.abs(x)))


def test_pack_unpack_split_complex():
    z = jnp.asarray((np.random.default_rng(1).standard_normal((4, 3))
                     + 1j * np.random.default_rng(2).standard_normal(
                         (4, 3))).astype(np.complex64))
    p = wire.pack(z, "bf16")
    # split-complex: re/im on a NEW trailing axis, 2 bytes each
    assert p.dtype == jnp.uint16 and p.shape == z.shape + (2,)
    back = wire.unpack(p, z.dtype, "bf16")
    assert back.dtype == z.dtype and back.shape == z.shape
    assert float(jnp.max(jnp.abs(back - z))) <= 2.0 ** -8 * float(
        jnp.max(jnp.abs(z)))


def test_pack_rejects_exact_dtypes():
    with pytest.raises(TypeError):
        wire.pack(jnp.arange(4, dtype=jnp.int32), "bf16")
    with pytest.raises(TypeError):
        wire.wire_itemsize(np.int32, "bf16")


def test_wire_bytes_shared_accounting():
    assert wire.wire_itemsize(np.float32, None) == 4
    assert wire.wire_itemsize(np.float32, "bf16") == 2
    assert wire.wire_itemsize(np.complex64, "bf16") == 4
    assert wire.wire_itemsize(np.complex128, "f16") == 4
    assert wire.wire_itemsize(np.float64, "bf16") == 2
    assert wire.wire_bytes(np.float32, "bf16", (8, 4)) == 64
    assert wire.cast_score_bytes(0, np.float32, "bf16") == 0
    assert wire.cast_score_bytes(64, np.float32, None) == 0
    assert wire.cast_score_bytes(64, np.float32, "bf16") > 0


# ---------------------------------------------------------------------------
# method plumbing
# ---------------------------------------------------------------------------


def test_method_labels_and_with_wire():
    # full-precision labels are byte-identical to the historical ones
    assert _method_label(AllToAll()) == "AllToAll"
    assert _method_label(Pipelined(chunks=2)) == \
        "Pipelined(chunks=2, base=AllToAll)"
    assert _method_label(AllToAll(wire_dtype="bf16")) == \
        "AllToAll[wire=bf16]"
    assert _method_label(Pipelined(chunks=2,
                                   base=Ring(wire_dtype="f16"))) == \
        "Pipelined(chunks=2, base=Ring[wire=f16])"
    m = with_wire(Pipelined(chunks=4), "bf16")
    assert m.base.wire_dtype == "bf16"
    assert with_wire(AllToAll(wire_dtype="bf16"), None) == \
        AllToAll(wire_dtype="bf16")
    # spellings canonicalize at construction: equal as cache keys
    assert AllToAll(wire_dtype="bfloat16") == AllToAll(wire_dtype="bf16")
    with pytest.raises(ValueError):
        with_wire(AllToAll(wire_dtype="bf16"), "f16")  # conflict
    with pytest.raises(ValueError):
        with_wire(Gspmd(), "bf16")  # partitioner-owned exchange
    with pytest.raises(ValueError):
        AllToAll(wire_dtype="fp8")


def test_auto_resolves_with_wire(hop):
    pin, pout = hop
    m = resolve_method(pin, pout, (), jnp.float32,
                       Auto(wire_dtype="bf16"))
    assert getattr(m, "wire_dtype", None) == "bf16"
    # wire-invariant choice: same winner type as the full-precision hop
    m0 = resolve_method(pin, pout, (), jnp.float32, Auto())
    assert type(m) is type(m0)


# ---------------------------------------------------------------------------
# acceptance pins: bit-identity off, halved HLO-pinned bytes on
# ---------------------------------------------------------------------------


def test_wire_none_bit_identical(hop):
    """wire_dtype=None IS today's behavior: same method object, same
    executable cache key, bit-identical results."""
    pin, pout = hop
    assert AllToAll() == AllToAll(wire_dtype=None)
    u = np.random.default_rng(3).standard_normal((16, 12, 20))
    x = PencilArray.from_global(pin, u)
    a = gather(transpose(x, pout, method=AllToAll()))
    b = gather(transpose(x, pout, method=AllToAll(wire_dtype=None)))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(a), u)


@pytest.mark.parametrize("method_wire", [
    AllToAll(wire_dtype="bf16"), Ring(wire_dtype="bf16"),
    Pipelined(chunks=2, base=AllToAll(wire_dtype="bf16"))])
def test_bf16_halves_priced_and_measured_bytes(hop, method_wire):
    """THE acceptance pin: priced bytes halve AND the compiled HLO's
    measured collective bytes equal the prediction (f32 and c64)."""
    pin, pout = hop
    full = type(method_wire)() if not isinstance(method_wire, Pipelined) \
        else Pipelined(chunks=2)
    for dt in (jnp.float32, jnp.complex64):
        c_full = transpose_cost(pin, pout, (), dt, full)
        c_wire = transpose_cost(pin, pout, (), dt, method_wire)
        for op in c_full:
            assert c_wire[op]["bytes"] * 2 == c_full[op]["bytes"]
            assert c_wire[op]["count"] == c_full[op]["count"]
        measured = spmd.trace_transpose(pin, pout, (), dt,
                                        method_wire).stats()
        assert measured == c_wire


def test_wire_numerics_within_model(hop):
    pin, pout = hop
    u = np.random.default_rng(4).standard_normal(
        (16, 12, 20)).astype(np.float32)
    x = PencilArray.from_global(pin, u)
    for w, eps in (("bf16", 2.0 ** -8), ("f16", 2.0 ** -11)):
        got = np.asarray(gather(transpose(
            x, pout, method=AllToAll(wire_dtype=w))))
        assert np.max(np.abs(got - u)) <= eps * np.max(np.abs(u))
        assert np.max(np.abs(got - u)) > 0  # it really quantized


def test_wire_transpose_cost_rejects_exact_dtype(hop):
    pin, pout = hop
    with pytest.raises(TypeError):
        transpose_cost(pin, pout, (), jnp.int32,
                       AllToAll(wire_dtype="bf16"))


# ---------------------------------------------------------------------------
# plans: wire through the FFT schedule
# ---------------------------------------------------------------------------


def test_plan_wire_halves_bytes_and_verifies(topo):
    ref = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32)
    w = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32,
                      wire_dtype="bf16")
    assert w.wire_dtype == "bf16"
    cf, cw = ref.collective_costs(), w.collective_costs()
    for op in cf:
        assert cw[op]["bytes"] * 2 == cf[op]["bytes"]
        assert cw[op]["count"] == cf[op]["count"]
    # compiled trace == prediction, both directions (the HLO pin)
    spmd.verify_plan(w)
    spmd.verify_plan(w, direction="backward")
    # fingerprints separate reduced- from full-precision traffic
    assert w.plan_key() != ref.plan_key()
    w2 = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32,
                       wire_dtype="bf16")
    assert w2.plan_key() == w.plan_key()
    # the method spelling reaches the same key (one truth)
    w3 = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32,
                       method=AllToAll(wire_dtype="bf16"))
    assert w3.plan_key() == w.plan_key() and w3.wire_dtype == "bf16"


def test_plan_wire_roundtrip_accuracy(topo):
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32, wire_dtype="bf16")
    host = np.random.default_rng(5).standard_normal(
        (16, 12, 10)).astype(np.float32)
    x = PencilArray.from_global(plan.input_pencil, host)
    back = np.asarray(gather(plan.backward(plan.forward(x))))
    scale = np.max(np.abs(host))
    err = np.max(np.abs(back - host))
    # 4 packed exchanges (2 hops each way) at bf16: comfortably inside
    # a few eps of headroom, and NOT bit-exact
    assert 0 < err <= 8 * 2.0 ** -8 * scale


def test_plan_wire_gspmd_method_rejected(topo):
    with pytest.raises(ValueError):
        PencilFFTPlan(topo, (16, 12, 10), method=Gspmd(),
                      wire_dtype="bf16")


# ---------------------------------------------------------------------------
# guard: tolerance model + typed exceedance
# ---------------------------------------------------------------------------


def test_guarded_wire_hop_passes_and_full_precision_detects(hop, tmp_path):
    pin, pout = hop
    u = np.random.default_rng(6).standard_normal(
        (16, 12, 20)).astype(np.float32)
    x = PencilArray.from_global(pin, u)
    with guard._forced("on", str(tmp_path)):
        y = transpose(x, pout, method=AllToAll(wire_dtype="bf16"))
        np.testing.assert_allclose(np.asarray(gather(y)), u, atol=0.02)
        # and the full-precision hop still passes its exact check
        y0 = transpose(x, pout, method=AllToAll())
        np.testing.assert_array_equal(np.asarray(gather(y0)), u)


def test_wire_drift_beyond_model_raises_typed():
    pre = np.array([100.0, 0.0, 1000.0, 0.0])
    drift = np.array([120.0, 0.0, 1000.0, 0.0])   # 2% of abs_sum: way out
    ok, kind = probes_match(pre, drift, 1000, np.float32,
                            wire_dtype="bf16")
    assert (ok, kind) == (False, "wire")
    with pytest.raises(WirePrecisionError) as ei:
        check_hop_probes("hop", pre, drift, 1000, np.float32,
                         wire_dtype="bf16")
    assert ei.value.wire_dtype == "bf16"
    assert isinstance(ei.value, IntegrityError)  # existing handlers catch


def test_wire_tolerance_widens_only_wire_hops():
    pre = np.array([100.0, 0.0, 1000.0, 0.0])
    small = np.array([100.0 + 1.0, 0.0, 1000.0, 0.0])  # 1e-3 of abs_sum
    assert probes_match(pre, small, 1000, np.float32,
                        wire_dtype="bf16") == (True, "ok")
    # the SAME drift on a full-precision hop is corruption
    assert probes_match(pre, small, 1000, np.float32) == (False, "sum")
    # more packed exchanges widen the bound linearly: a drift just
    # past the 1-hop bound (~6.8 abs here) passes the 4-hop bound
    bigger = np.array([100.0 + 10.0, 0.0, 1000.0, 0.0])
    assert probes_match(pre, bigger, 1000, np.float32,
                        wire_dtype="bf16", wire_hops=1)[0] is False
    assert probes_match(pre, bigger, 1000, np.float32,
                        wire_dtype="bf16", wire_hops=4)[0] is True


def test_wire_rtol_env_override(monkeypatch):
    assert wire.wire_rtol(None, 100) == 0.0
    base = wire.wire_rtol("bf16", 100)
    assert 2.0 ** -9 <= base <= 2.0 ** -6
    monkeypatch.setenv("PENCILARRAYS_TPU_GUARD_WIRE_RTOL", "0.25")
    assert wire.wire_rtol("bf16", 100) == 0.25
    monkeypatch.delenv("PENCILARRAYS_TPU_GUARD_WIRE_RTOL")
    assert wire.wire_rtol("bf16", 100) == base


def test_guarded_routed_reshard_with_wire(topo, tmp_path):
    pin = Pencil(topo, (16, 12, 20), (1, 2))
    dest = Pencil(topo, (16, 12, 20), (0, 1))
    u = np.random.default_rng(8).standard_normal(
        (16, 12, 20)).astype(np.float32)
    x = PencilArray.from_global(pin, u)
    with guard._forced("on", str(tmp_path)):
        out = reshard(x, dest, method=AllToAll(wire_dtype="bf16"))
    np.testing.assert_allclose(np.asarray(gather(out)), u, atol=0.02)


# ---------------------------------------------------------------------------
# router: wire-aware pricing and the HBM admission win
# ---------------------------------------------------------------------------


def test_route_planner_admits_wire_edge_under_hbm_limit(topo):
    """The ROADMAP claim: reduced-precision edges fit SINGLE-SHOT under
    an ``hbm_limit`` where full-precision ones do not — the packed
    operand is half the HBM high-water mark's exchange share.  Since
    ISSUE 14 the full-precision plan is no longer pruned outright at
    that limit: the planner *synthesizes* a time-sliced (chunked)
    route for it instead — the wire's win becomes single-shot
    admission (count ×1) vs the chunked schedule's count ×K."""
    from pencilarrays_tpu.parallel.routing import plan_reshard_route
    from pencilarrays_tpu.parallel.transpositions import Pipelined

    pin = Pencil(topo, (16, 12, 20), (1, 2))
    dest = Pencil(topo, (16, 12, 20), (0, 1))
    # donate=True isolates the operand accounting (no pinned-source
    # surcharge), as the original PR-13 pin did
    full = plan_reshard_route(pin, dest, (), np.float32,
                              method=AllToAll(), donate=True)
    wired = plan_reshard_route(pin, dest, (), np.float32,
                               method=AllToAll(wire_dtype="bf16"),
                               donate=True)
    assert wired.peak_hbm_bytes < full.peak_hbm_bytes
    lim = (full.peak_hbm_bytes + wired.peak_hbm_bytes) // 2
    chunked = plan_reshard_route(pin, dest, (), np.float32,
                                 method=AllToAll(), hbm_limit=lim,
                                 donate=True)
    admitted = plan_reshard_route(pin, dest, (), np.float32,
                                  method=AllToAll(wire_dtype="bf16"),
                                  hbm_limit=lim, donate=True)
    # full precision: only a SYNTHESIZED chunked route fits the limit
    assert chunked.use_route and chunked.verdict == "routed:hbm"
    assert any(isinstance(h.method, Pipelined) for h in chunked.hops)
    assert chunked.peak_hbm_bytes <= lim
    # the wire edge fits single-shot — no chunking, half the bytes
    assert admitted.use_route
    assert not any(isinstance(h.method, Pipelined)
                   for h in admitted.hops)
    assert all(h.method.wire_dtype == "bf16" for h in admitted.hops)
    # and the fused routed chains' compiled traces match the per-hop
    # priced costs op-for-op (halved bytes / multiplied counts)
    spmd.verify_route(admitted, (), np.float32)
    spmd.verify_route(chunked, (), np.float32)


# ---------------------------------------------------------------------------
# dispatch log: wire bytes certified (satellite bugfix)
# ---------------------------------------------------------------------------


def test_verify_dispatch_log_rejects_wire_byte_mismatch(topo):
    from pencilarrays_tpu.analysis.errors import ScheduleMismatchError
    from pencilarrays_tpu.engine import DispatchRecord

    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32, wire_dtype="bf16")
    good = plan.predicted_wire_bytes(())

    def rec(seq, wire_bytes):
        return DispatchRecord(
            enqueue_seq=seq, issue_seq=seq, label=f"fft:{seq}",
            outcome="ok", queued_s=0.0, run_s=0.0,
            meta={"plan": plan, "direction": "forward",
                  "extra_dims": (), "wire_dtype": "bf16",
                  "wire_bytes": wire_bytes})

    report = spmd.verify_dispatch_log([rec(1, good)], source="t")
    assert report["wire_checked"] == 1
    assert report["verified_traces"] == 1
    # a dispatch logged at FULL-precision bytes against the reduced
    # plan's priced schedule must fail typed, not certify cleanly
    with pytest.raises(ScheduleMismatchError) as ei:
        spmd.verify_dispatch_log([rec(1, good), rec(2, good * 2)],
                                 source="t")
    assert ei.value.op == "wire-bytes"
    # records without the stamp stay certified the historical way
    bare = DispatchRecord(enqueue_seq=3, issue_seq=3, label="fft:3",
                          outcome="ok", queued_s=0.0, run_s=0.0,
                          meta={"plan": plan, "direction": "forward",
                                "extra_dims": ()})
    report = spmd.verify_dispatch_log([bare], source="t")
    assert report["wire_checked"] == 0 and report["verified_traces"] == 1


def test_forward_async_meta_carries_wire(topo):
    from pencilarrays_tpu.engine import get_engine

    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32, wire_dtype="f16")
    u = plan.allocate_input()
    fut = plan.forward_async(u)
    fut.result(timeout=60)
    eng = get_engine()
    mine = [r for r in eng.dispatch_log()
            if r.meta.get("plan") is plan]
    assert mine, "dispatch not logged"
    assert mine[-1].meta["wire_dtype"] == "f16"
    assert mine[-1].meta["wire_bytes"] == plan.predicted_wire_bytes(())
    spmd.verify_dispatch_log(mine, source="wire-async")


def test_measure_auto_downgrade_keeps_wire(topo):
    """Regression (review): the planners' measure->estimate Auto
    downgrade must keep the wire_dtype — a measure-mode wire plan was
    scored/routed at full-precision bytes."""
    from pencilarrays_tpu.parallel.routing import plan_reshard_route

    pin = Pencil(topo, (16, 12, 20), (1, 2))
    dest = Pencil(topo, (16, 12, 20), (0, 1))
    route = plan_reshard_route(
        pin, dest, (), np.float32,
        method=Auto(mode="measure", wire_dtype="bf16"))
    assert route.hops, "expected a routed plan"
    assert all(h.method.wire_dtype == "bf16" for h in route.hops)
    full = plan_reshard_route(pin, dest, (), np.float32, method=Auto())
    wired_bytes = sum(v["bytes"] for h in route.hops
                      for v in h.cost.values())
    full_bytes = sum(v["bytes"] for h in full.hops
                     for v in h.cost.values())
    assert wired_bytes * 2 == full_bytes
    # and the decomposition scorer prices the wire through the same
    # downgrade (probe plans never benchmark)
    p = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32,
                      method=Auto(mode="measure", wire_dtype="bf16"),
                      decomposition="auto")
    assert p.wire_dtype == "bf16"
    pf = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32,
                       method=Auto(mode="measure"),
                       decomposition="auto")
    w_score = p.decomposition_verdict["candidates"]
    f_score = pf.decomposition_verdict["candidates"]
    by_dims = {tuple(c["dims"]): c["predicted_bytes"] for c in f_score}
    for c in w_score:
        assert c["predicted_bytes"] * 2 == by_dims[tuple(c["dims"])]


# ---------------------------------------------------------------------------
# fp8 wire (ISSUE 19): per-tile scaling, quartered bytes, typed envelope
# ---------------------------------------------------------------------------


def test_canonical_fp8_spellings():
    for spelling in ("fp8_e4m3", "e4m3", "float8_e4m3", "float8_e4m3fn",
                     "fp8-e4m3"):
        assert wire.canonical_wire_dtype(spelling) == "fp8_e4m3"
    for spelling in ("fp8_e5m2", "e5m2", "float8_e5m2", "fp8-e5m2"):
        assert wire.canonical_wire_dtype(spelling) == "fp8_e5m2"
    # bare "fp8" stays ambiguous on purpose: the two formats trade
    # mantissa for range and the caller must pick
    with pytest.raises(ValueError):
        wire.canonical_wire_dtype("fp8")
    # the method spelling canonicalizes too (cache-key equality)
    assert AllToAll(wire_dtype="e4m3") == AllToAll(wire_dtype="fp8_e4m3")


def test_fp8_tile_axis_rule():
    # largest extent NOT an exchange axis; ties break to lowest index
    assert wire.fp8_tile_axis((16, 12, 20), 0, 1) == 2
    assert wire.fp8_tile_axis((16, 12, 20), 1, 2) == 0
    assert wire.fp8_tile_axis((16, 12, 20), 0, 2) == 1
    assert wire.fp8_tile_axis((16, 12, 20, 7), 1, 2) == 0   # extra dim loses
    assert wire.fp8_tile_axis((16, 12, 20, 64), 1, 2) == 3  # ...until bigger
    assert wire.fp8_tile_axis((8, 8, 8), 0, 1) == 2
    # a 2-D exchange operand has no free axis to tile along
    with pytest.raises(ValueError, match="16-bit wire"):
        wire.fp8_tile_axis((16, 12), 0, 1)


@pytest.mark.parametrize("w", ["fp8_e4m3", "fp8_e5m2"])
@pytest.mark.parametrize("shape,axes", [
    ((16, 12, 20), (0, 1)),          # tile axis 2, one partial tile
    ((4, 3, 300), (0, 1)),           # tile axis 2, 300 = 256 + 44
    ((512, 3, 5), (1, 2)),           # tile axis 0, exactly 2 tiles
    ((7, 5, 9, 6), (0, 2)),          # 4-D, tile axis 3? no: axis 3=6 < 9?
])
def test_fp8_pack_unpack_roundtrip_bound(w, shape, axes):
    rng = np.random.default_rng(hash((w, shape)) % 2 ** 31)
    # mixed magnitudes per tile stress the per-tile (not per-array)
    # scaling: columns spanning 6 orders of magnitude still come back
    # within the format's relative bound of their own tile max
    x = (rng.standard_normal(shape)
         * 10.0 ** rng.integers(-3, 3, size=shape)).astype(np.float32)
    xj = jnp.asarray(x)
    p = wire.pack(xj, w, axes=axes)
    assert p.dtype == jnp.uint8
    back = np.asarray(wire.unpack(p, xj.dtype, w, axes=axes,
                                  orig_shape=shape))
    t = wire.fp8_tile_axis(shape, *axes)
    # per-tile relative bound: |err| <= eps/2 * tile_amax
    eps = {"fp8_e4m3": 2.0 ** -3, "fp8_e5m2": 2.0 ** -2}[w]
    amax = np.max(np.abs(np.moveaxis(x, t, -1)), axis=-1, keepdims=True)
    err = np.max(np.abs(np.moveaxis(back - x, t, -1))
                 / np.maximum(amax, 1e-30))
    assert err <= 0.5 * eps * 1.001
    assert err > 0  # it really quantized


def test_fp8_pack_complex_roundtrip():
    shape = (16, 12, 20)
    rng = np.random.default_rng(11)
    z = (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64)
    p = wire.pack(jnp.asarray(z), "fp8_e4m3", axes=(0, 1))
    assert p.dtype == jnp.uint8
    back = np.asarray(wire.unpack(p, jnp.complex64, "fp8_e4m3",
                                  axes=(0, 1), orig_shape=shape))
    assert back.dtype == np.complex64
    rel = np.linalg.norm(back - z) / np.linalg.norm(z)
    assert 0 < rel <= 0.5 * 2.0 ** -3


def test_fp8_denormal_and_overflow_edges():
    # values far below the tile max vanish (per-tile scale trades small
    # values for range — the documented contract), but a tile made ONLY
    # of tiny values gets its own scale and keeps them
    shape = (1, 1, 256)
    tiny = np.full(shape, 1e-30, dtype=np.float32)
    back = np.asarray(wire.unpack(
        wire.pack(jnp.asarray(tiny), "fp8_e4m3", axes=(0, 1)),
        jnp.float32, "fp8_e4m3", axes=(0, 1), orig_shape=shape))
    np.testing.assert_allclose(back, tiny, rtol=0.5 * 2.0 ** -3)
    # huge finite values scale down and back up without overflow
    huge = np.full(shape, 3e38, dtype=np.float32)
    back = np.asarray(wire.unpack(
        wire.pack(jnp.asarray(huge), "fp8_e4m3", axes=(0, 1)),
        jnp.float32, "fp8_e4m3", axes=(0, 1), orig_shape=shape))
    assert np.all(np.isfinite(back))
    np.testing.assert_allclose(back, huge, rtol=0.5 * 2.0 ** -3)
    # an all-zero tile keeps scale 1 and decodes to exact zeros
    zero = np.zeros(shape, dtype=np.float32)
    back = np.asarray(wire.unpack(
        wire.pack(jnp.asarray(zero), "fp8_e4m3", axes=(0, 1)),
        jnp.float32, "fp8_e4m3", axes=(0, 1), orig_shape=shape))
    np.testing.assert_array_equal(back, zero)


def test_fp8_nan_passthrough():
    shape = (2, 1, 300)
    x = np.random.default_rng(12).standard_normal(shape).astype(np.float32)
    x[0, 0, 7] = np.nan
    x[1, 0, 299] = np.nan
    back = np.asarray(wire.unpack(
        wire.pack(jnp.asarray(x), "fp8_e4m3", axes=(0, 1)),
        jnp.float32, "fp8_e4m3", axes=(0, 1), orig_shape=shape))
    assert np.isnan(back[0, 0, 7]) and np.isnan(back[1, 0, 299])
    # the poisoned taps do NOT poison their tiles' scales: every other
    # element still meets the quantization bound
    finite = np.isfinite(x)
    assert np.all(np.isfinite(back[finite]))
    rel = np.max(np.abs((back - x)[finite]) / np.max(np.abs(x[finite])))
    assert rel <= 0.5 * 2.0 ** -3


def test_fp8_requires_axes():
    x = jnp.ones((4, 4, 4))
    with pytest.raises(ValueError):
        wire.pack(x, "fp8_e4m3")
    with pytest.raises(ValueError):
        wire.wire_bytes(np.float32, "fp8_e4m3", (4, 4, 4))


def test_fp8_wire_bytes_accounting():
    # payload n_t bytes + 4 bytes of f32 scale per 256-tile, per row
    assert wire.wire_itemsize(np.float32, "fp8_e4m3") == 1
    assert wire.wire_itemsize(np.complex64, "fp8_e4m3") == 2
    # (16, 12, 20) exchanged on (0, 1): tile axis 2 (n_t=20, 1 tile)
    assert wire.wire_bytes(np.float32, "fp8_e4m3", (16, 12, 20),
                           axes=(0, 1)) == 16 * 12 * (20 + 4)
    # 300-long tile axis: 2 tiles -> 8 scale bytes per row
    assert wire.wire_bytes(np.float32, "fp8_e5m2", (4, 3, 300),
                           axes=(0, 1)) == 4 * 3 * (300 + 8)
    # complex doubles both payload and scale planes
    assert wire.wire_bytes(np.complex64, "fp8_e4m3", (16, 12, 20),
                           axes=(0, 1)) == 2 * 16 * 12 * (20 + 4)
    # asymptotically /4 vs f32: overhead is 4/256 of payload
    big = wire.wire_bytes(np.float32, "fp8_e4m3", (8, 8, 4096),
                          axes=(0, 1))
    full = 8 * 8 * 4096 * 4
    assert full / big == pytest.approx(4.0, rel=0.02)


@pytest.mark.parametrize("method_fp8", [
    AllToAll(wire_dtype="fp8_e4m3"), Ring(wire_dtype="fp8_e4m3"),
    Pipelined(chunks=2, base=AllToAll(wire_dtype="fp8_e5m2"))])
def test_fp8_priced_equals_measured_bytes(hop, method_fp8):
    """THE fp8 acceptance pin: the compiled HLO's collective bytes
    equal the prediction exactly — scales ride the SAME exchange."""
    pin, pout = hop
    for dt in (jnp.float32, jnp.complex64):
        c = transpose_cost(pin, pout, (), dt, method_fp8)
        measured = spmd.trace_transpose(pin, pout, (), dt,
                                        method_fp8).stats()
        assert measured == c


def test_fp8_transpose_numerics_and_identity(hop):
    pin, pout = hop
    u = np.random.default_rng(13).standard_normal(
        (16, 12, 20)).astype(np.float32)
    x = PencilArray.from_global(pin, u)
    for w, eps in (("fp8_e4m3", 2.0 ** -3), ("fp8_e5m2", 2.0 ** -2)):
        got = np.asarray(gather(transpose(
            x, pout, method=AllToAll(wire_dtype=w))))
        assert 0 < np.max(np.abs(got - u)) <= 0.5 * eps * np.max(
            np.abs(u)) * 1.001


def test_fp8_plan_verifies_and_fingerprints(topo):
    ref = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32)
    w = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32,
                      wire_dtype="fp8_e4m3")
    assert w.wire_dtype == "fp8_e4m3"
    spmd.verify_plan(w)
    spmd.verify_plan(w, direction="backward")
    assert w.plan_key() != ref.plan_key()
    bf = PencilFFTPlan(topo, (16, 12, 10), real=True, dtype=jnp.float32,
                       wire_dtype="bf16")
    assert w.plan_key() != bf.plan_key()
    # roundtrip accuracy within the fp8 tile-scaled model
    host = np.random.default_rng(14).standard_normal(
        (16, 12, 10)).astype(np.float32)
    x = PencilArray.from_global(w.input_pencil, host)
    back = np.asarray(gather(w.backward(w.forward(x))))
    rel = np.linalg.norm(back - host) / np.linalg.norm(host)
    assert 0 < rel <= 0.08


def test_fp8_pipelined_plan_chunked_bytes_verify(topo):
    """fp8 breaks chunk-count byte invariance (each chunk ships its own
    scale plane): the pricer charges per-chunk honestly and the HLO pin
    must still hold on the fused pipelined schedule."""
    plan = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32, wire_dtype="fp8_e4m3",
                         pipeline=2)
    spmd.verify_plan(plan)
    spmd.verify_plan(plan, direction="backward")
    k1 = PencilFFTPlan(topo, (16, 12, 10), real=True,
                       dtype=jnp.float32, wire_dtype="fp8_e4m3")
    b2 = sum(v["bytes"] for v in plan.collective_costs().values())
    b1 = sum(v["bytes"] for v in k1.collective_costs().values())
    assert b2 > b1  # more chunks -> more scale planes, priced honestly


def test_fp8_guard_envelope_and_typed_exceedance(hop, tmp_path):
    pin, pout = hop
    u = np.random.default_rng(15).standard_normal(
        (16, 12, 20)).astype(np.float32)
    x = PencilArray.from_global(pin, u)
    with guard._forced("on", str(tmp_path)):
        y = transpose(x, pout, method=AllToAll(wire_dtype="fp8_e4m3"))
        np.testing.assert_allclose(np.asarray(gather(y)), u, atol=0.25)
    # drift beyond the fp8 envelope raises typed WirePrecisionError
    # (wire_rtol("fp8_e4m3", 1000) ~ 0.22 of the 1100 abs-sum ~ 242)
    pre = np.array([100.0, 0.0, 1000.0, 0.0])
    drift = np.array([400.0, 0.0, 1000.0, 0.0])
    ok, kind = probes_match(pre, drift, 1000, np.float32,
                            wire_dtype="fp8_e4m3")
    assert (ok, kind) == (False, "wire")
    with pytest.raises(WirePrecisionError) as ei:
        check_hop_probes("hop", pre, drift, 1000, np.float32,
                         wire_dtype="fp8_e4m3")
    assert ei.value.wire_dtype == "fp8_e4m3"
    # drift INSIDE the fp8 envelope (but outside bf16's ~7.5) passes
    small = np.array([130.0, 0.0, 1000.0, 0.0])
    assert probes_match(pre, small, 1000, np.float32,
                        wire_dtype="fp8_e4m3")[0] is True
    assert probes_match(pre, small, 1000, np.float32,
                        wire_dtype="bf16")[0] is False


def test_fp8_routed_reshard_verifies(topo):
    from pencilarrays_tpu.parallel.routing import plan_reshard_route

    pin = Pencil(topo, (16, 12, 20), (1, 2))
    dest = Pencil(topo, (16, 12, 20), (0, 1))
    route = plan_reshard_route(pin, dest, (), np.float32,
                               method=AllToAll(wire_dtype="fp8_e4m3"))
    assert route.hops
    assert all(h.method.wire_dtype == "fp8_e4m3" for h in route.hops)
    spmd.verify_route(route, (), np.float32)
    u = np.random.default_rng(16).standard_normal(
        (16, 12, 20)).astype(np.float32)
    x = PencilArray.from_global(pin, u)
    out = np.asarray(gather(reshard(
        x, dest, method=AllToAll(wire_dtype="fp8_e4m3"))))
    assert np.max(np.abs(out - u)) <= 0.5 * 2.0 ** -3 * np.max(
        np.abs(u)) * 1.001


def test_plan_with_wire_dtype_variants(topo):
    full = PencilFFTPlan(topo, (16, 12, 10), real=True,
                         dtype=jnp.float32)
    v = full.with_wire_dtype("fp8_e4m3")
    direct = PencilFFTPlan(topo, (16, 12, 10), real=True,
                           dtype=jnp.float32, wire_dtype="fp8_e4m3")
    assert v.wire_dtype == "fp8_e4m3"
    assert v.plan_key() == direct.plan_key()
    assert v.plan_key() != full.plan_key()
    # variant cache: same object back, and no-op for the current wire
    assert full.with_wire_dtype("fp8_e4m3") is v
    assert full.with_wire_dtype(None) is full
    assert v.with_wire_dtype("e4m3") is v
    # downgrading a bf16 plan reaches fp8, not a bf16-of-bf16
    bf = full.with_wire_dtype("bf16")
    assert bf.with_wire_dtype("fp8_e4m3").plan_key() == direct.plan_key()
